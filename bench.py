"""Driver benchmark: the BASELINE.json headline metric through the full stack.

Runs examples/benchmark-numpy.py (sum of squares over 1e8 random doubles) via
a real Execute — orchestrator → pooled sandbox → C++ executor → warm JAX
runner → numpy dispatch shim → XLA on whatever accelerator this machine
exposes — and compares against a measured in-sandbox CPU/numpy baseline
(dispatch shim off), i.e. exactly what the reference stack would do.

Prints ONE JSON line:
  {"metric": ..., "value": <TPU GFLOPS>, "unit": "GFLOPS", "vs_baseline": <x over CPU numpy>}
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import statistics
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor  # noqa: E402
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

BENCH_SOURCE = (REPO_ROOT / "examples" / "benchmark-numpy.py").read_text()
MATMUL_SOURCE = (REPO_ROOT / "examples" / "benchmark-matmul.py").read_text()
ATTENTION_SOURCE = (REPO_ROOT / "examples" / "benchmark-attention.py").read_text()
METRIC = "benchmark-numpy.py GFLOPS/chip via Execute (1e8 sum-of-squares)"
ATTN_RE = re.compile(r"ATTN_TFLOPS=([0-9.]+)")
GFLOPS_RE = re.compile(r"GFLOPS=([0-9.]+)")
SINGLE_SHOT_RE = re.compile(r"GFLOPS_single_shot=([0-9.]+)")
TFLOPS_RE = re.compile(r"TFLOPS=([0-9.]+)")
MFU_RE = re.compile(r"MFU_vs_v5e_peak_pct=([0-9.]+)")


def log(msg: str) -> None:
    """Progress to stderr: stdout must stay one clean JSON line, and when the
    bench dies the driver's captured tail must say which stage died."""
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


async def run_gflops(dispatch: bool, runs: int, tmp: Path) -> tuple[float, dict]:
    config = Config(
        file_storage_path=str(tmp / f"storage-{dispatch}"),
        local_sandbox_root=str(tmp / f"sb-{dispatch}"),
        executor_pod_queue_target_length=1,
        default_execution_timeout=600.0,
        jax_compilation_cache_dir=str(tmp / "jax-cache"),
    )
    backend = LocalSandboxBackend(
        config, warm_import_jax=dispatch, numpy_dispatch=dispatch
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log(f"filling pool (dispatch={dispatch})...")
        await executor.fill_pool()
        samples: list[float] = []
        single_shots: list[float] = []
        info: dict = {}
        for i in range(runs):
            log(f"run {i} (dispatch={dispatch})...")
            t0 = time.perf_counter()
            result = await executor.execute(BENCH_SOURCE, timeout=600.0)
            elapsed = time.perf_counter() - t0
            if result.exit_code != 0:
                raise RuntimeError(f"bench execute failed: {result.stderr[-800:]}")
            match = GFLOPS_RE.search(result.stdout)
            if not match:
                raise RuntimeError(f"no GFLOPS line in: {result.stdout[-400:]}")
            gflops = float(match.group(1))
            single = SINGLE_SHOT_RE.search(result.stdout)
            if single:
                single_shots.append(float(single.group(1)))
            backend_line = next(
                (l for l in result.stdout.splitlines() if l.startswith("backend:")),
                "backend: ?",
            )
            info = {
                "run": i,
                "execute_wall_s": round(elapsed, 3),
                "array_type": backend_line.split(":", 1)[1].strip(),
                "phases": {k: round(v, 4) for k, v in result.phases.items()},
            }
            log(f"run {i}: {gflops:.3f} GFLOPS ({info['array_type']})")
            samples.append(gflops)
        # Run 0 includes first-compile; steady state = the rest (SURVEY §6 /
        # VERDICT r2 #3: N>=3, report best and median excluding compile).
        steady = samples[1:] if len(samples) > 1 else samples
        info["gflops_samples"] = [round(s, 3) for s in samples]
        info["gflops_median"] = round(statistics.median(steady), 3)
        if single_shots:
            info["gflops_single_shot_best"] = round(max(single_shots), 3)
        return max(steady), info
    finally:
        await executor.close()


async def run_matmul(tmp: Path) -> dict:
    """Compute-bound config: chained bf16 matmuls (pure JAX user code via
    Execute). Reports achieved TFLOPS + MFU vs v5e bf16 peak."""
    config = Config(
        file_storage_path=str(tmp / "storage-mm"),
        local_sandbox_root=str(tmp / "sb-mm"),
        executor_pod_queue_target_length=1,
        default_execution_timeout=600.0,
        jax_compilation_cache_dir=str(tmp / "jax-cache"),
    )
    # numpy_dispatch puts the repo on the sandbox path — the attention bench
    # imports the framework's Pallas kernel; matmul is pure jax either way.
    backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log("matmul: filling pool...")
        await executor.fill_pool()
        best: dict = {}
        for i in range(2):
            log(f"matmul run {i}...")
            result = await executor.execute(MATMUL_SOURCE, timeout=600.0)
            if result.exit_code != 0:
                raise RuntimeError(f"matmul execute failed: {result.stderr[-800:]}")
            tflops_m = TFLOPS_RE.search(result.stdout)
            if not tflops_m:
                raise RuntimeError(f"no TFLOPS line in: {result.stdout[-400:]}")
            tflops = float(tflops_m.group(1))
            mfu_m = MFU_RE.search(result.stdout)
            log(f"matmul run {i}: {tflops:.2f} TFLOPS")
            if not best or tflops > best["matmul_tflops"]:
                best = {
                    "matmul_tflops": tflops,
                    "matmul_mfu_vs_v5e_peak_pct": (
                        float(mfu_m.group(1)) if mfu_m else None
                    ),
                }
        # Long-context fused attention (Pallas flash kernel) through Execute.
        log("flash attention (t=16384)...")
        result = await executor.execute(ATTENTION_SOURCE, timeout=600.0)
        if result.exit_code == 0:
            attn = ATTN_RE.search(result.stdout)
            if attn:
                best["flash_attention_16k_tflops"] = float(attn.group(1))
                log(f"flash attention: {attn.group(1)} TFLOPS causal")
        else:
            log(f"flash attention failed (non-fatal): {result.stderr[-300:]}")
        return best
    finally:
        await executor.close()


async def cold_start_p50(tmp: Path, samples: int = 5) -> float:
    """Execute RPC latency with a warm pool (the p50 the user sees)."""
    config = Config(
        file_storage_path=str(tmp / "storage-lat"),
        local_sandbox_root=str(tmp / "sb-lat"),
        executor_pod_queue_target_length=2,
        jax_compilation_cache_dir=str(tmp / "jax-cache"),
    )
    backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log("p50: filling pool...")
        await executor.fill_pool()
        latencies = []
        for i in range(samples):
            t0 = time.perf_counter()
            result = await executor.execute("print(21 * 2)")
            latencies.append(time.perf_counter() - t0)
            assert result.exit_code == 0
            log(f"p50 sample {i}: {latencies[-1]:.3f}s")
            # let the refill task restore the pool before the next sample
            await executor.fill_pool()
        return statistics.median(latencies)
    finally:
        await executor.close()


def prime_accelerator() -> None:
    """One clean-exiting subprocess that imports jax and touches the devices
    BEFORE any sandbox spawns. First-ever TPU init on a cold host pages in
    the whole jax/libtpu stack and establishes the device session — minutes,
    sometimes longer than any sane per-sandbox budget. Paying it here, in a
    process that exits cleanly (never killed mid-init — killing a client
    mid-init can wedge the device for the next one), makes every subsequent
    sandbox warm-up fast. No timeout on purpose."""
    import subprocess

    log("priming accelerator (first-init page-in, may take minutes)...")
    t0 = time.perf_counter()
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import jax, jax.numpy as jnp;"
            "print(jax.devices());"
            "jnp.add(jnp.ones(()), 1.0).block_until_ready()",
        ],
        capture_output=True,
        text=True,
    )
    log(
        f"prime done in {time.perf_counter() - t0:.1f}s rc={proc.returncode} "
        f"{(proc.stdout or proc.stderr).strip().splitlines()[-1:]}"
    )


async def main() -> None:
    import tempfile

    prime_accelerator()
    with tempfile.TemporaryDirectory(prefix="bench-") as tmp_str:
        tmp = Path(tmp_str)
        tpu_gflops, tpu_info = await run_gflops(dispatch=True, runs=4, tmp=tmp)
        matmul = await run_matmul(tmp)
        cpu_gflops, _ = await run_gflops(dispatch=False, runs=1, tmp=tmp)
        p50 = await cold_start_p50(tmp)

    line = {
        "metric": METRIC,
        "value": round(tpu_gflops, 3),
        "unit": "GFLOPS",
        "vs_baseline": round(tpu_gflops / cpu_gflops, 2) if cpu_gflops else None,
        "extra": {
            "cpu_numpy_gflops": round(cpu_gflops, 3),
            "execute_p50_warm_pool_s": round(p50, 4),
            "tpu_run": tpu_info,
            **matmul,
        },
    }
    print(json.dumps(line))


def _emit_error(kind: str) -> None:
    """The degraded stdout contract: still exactly one parseable JSON line,
    with an `error` field instead of a measurement."""
    log(f"bench failed: {kind}")
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "GFLOPS",
                "vs_baseline": None,
                "error": kind[:500],
            }
        ),
        flush=True,
    )


def _run_with_deadline() -> None:
    """Run the bench under an overall deadline, degrading to a parseable
    JSON error line instead of hanging or crashing with a bare traceback.

    The failure this guards: a test-rig device wedged by some earlier
    client killed mid-init makes every TPU attach hang; without a deadline
    the bench would sit in spawn-retry loops for hours (3 spawn attempts x
    a deliberately generous 600 s warm budget x several configs) and the
    harness would record nothing at all. One JSON line with an `error`
    field keeps the run auditable either way."""
    try:
        deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "") or 2700)
    except ValueError:
        deadline_s = 2700.0
    deadline_msg = f"deadline of {deadline_s:.0f}s exceeded (accelerator hung?)"

    # Thread backstop: the primer is a BLOCKING subprocess.run (deliberately
    # never killed — killing a client mid-TPU-init is what wedges devices),
    # and asyncio.wait_for cannot preempt a blocked event loop. The timer
    # emits the error line and exits the bench; the primer child is left to
    # finish or wait on its own (orphaned, still never killed mid-init).
    import threading

    def _hard_deadline() -> None:
        _emit_error(deadline_msg)
        os._exit(1)

    timer = threading.Timer(deadline_s + 30.0, _hard_deadline)
    timer.daemon = True
    timer.start()
    try:
        asyncio.run(asyncio.wait_for(main(), timeout=deadline_s))
        timer.cancel()
    except Exception as e:  # noqa: BLE001 — the output contract is one JSON line
        # Cancel BEFORE emitting: teardown of wedged sandboxes can take long
        # enough that the backstop would otherwise fire concurrently and put
        # a second JSON line on stdout.
        timer.cancel()
        if isinstance(e, (asyncio.TimeoutError, TimeoutError)):
            _emit_error(deadline_msg)
        else:
            _emit_error(f"{type(e).__name__}: {e}")
        sys.exit(1)


if __name__ == "__main__":
    _run_with_deadline()
