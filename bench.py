"""Driver benchmark: the BASELINE.json headline metric through the full stack.

Runs examples/benchmark-numpy.py (sum of squares over 1e8 random doubles) via
a real Execute — orchestrator → pooled sandbox → C++ executor → warm JAX
runner → numpy dispatch shim → XLA on whatever accelerator this machine
exposes — and compares against a measured in-sandbox CPU/numpy baseline
(dispatch shim off), i.e. exactly what the reference stack would do.

Prints ONE JSON line:
  {"metric": ..., "value": <TPU GFLOPS>, "unit": "GFLOPS", "vs_baseline": <x over CPU numpy>}
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import statistics
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO_ROOT))

from bee_code_interpreter_fs_tpu.config import Config  # noqa: E402
from bee_code_interpreter_fs_tpu.services.backends.local import (  # noqa: E402
    LocalSandboxBackend,
)
from bee_code_interpreter_fs_tpu.services.code_executor import CodeExecutor  # noqa: E402
from bee_code_interpreter_fs_tpu.services.storage import Storage  # noqa: E402

BENCH_SOURCE = (REPO_ROOT / "examples" / "benchmark-numpy.py").read_text()
MATMUL_SOURCE = (REPO_ROOT / "examples" / "benchmark-matmul.py").read_text()
ATTENTION_SOURCE = (REPO_ROOT / "examples" / "benchmark-attention.py").read_text()
QUANT_SOURCE = (REPO_ROOT / "examples" / "benchmark-quant.py").read_text()
SERVING_SOURCE = (REPO_ROOT / "examples" / "benchmark-serving.py").read_text()
ENGINE_TOKS_RE = re.compile(r"ENGINE_TOKS_PER_S=([0-9.]+)")
PAGED_TOKS_RE = re.compile(r"PAGED_TOKS_PER_S=([0-9.]+)")
ENGINE_SPEEDUP_RE = re.compile(r"ENGINE_SPEEDUP=([0-9.]+)")
METRIC = "benchmark-numpy.py GFLOPS/chip via Execute (1e8 sum-of-squares)"
INT8_SPEEDUP_RE = re.compile(r"INT8_DECODE_SPEEDUP=([0-9.]+)")
INT8_TOKS_RE = re.compile(r"INT8_DECODE_TOKS=([0-9.]+)")
BF16_TOKS_RE = re.compile(r"BF16_DECODE_TOKS=([0-9.]+)")

# Results accumulate here as each leg completes, so a deadline or mid-run
# failure still reports everything measured up to that point (round 3's
# artifact was empty because nothing partial ever reached stdout).
PARTIAL: dict = {}

# Absolute perf_counter() timestamp of the overall deadline, set by
# _run_with_deadline; inner legs clamp their timeouts against it.
_DEADLINE_AT: float | None = None
ATTN_RE = re.compile(r"ATTN_TFLOPS=([0-9.]+)")
GFLOPS_RE = re.compile(r"GFLOPS=([0-9.]+)")
SINGLE_SHOT_RE = re.compile(r"GFLOPS_single_shot=([0-9.]+)")

# Compilation cache SURVIVES across bench runs (and is shared with the
# driver's round-end invocation on the same machine): a per-run tmp dir made
# every run recompile every fused program from scratch, which is exactly what
# starved the int8 leg of its budget. Content-addressed, so staleness is not
# a concern; override with BENCH_JAX_CACHE.
# Outside /tmp: the benched sandboxes' /reset wipes /tmp-resident extra
# dirs, and the whole point of the bench cache is surviving generations.
_JAX_CACHE_DIR = os.environ.get(
    "BENCH_JAX_CACHE", "/var/tmp/bee_bench_jax_cache"
)
TFLOPS_RE = re.compile(r"TFLOPS=([0-9.]+)")
MFU_RE = re.compile(r"MFU_vs_v5e_peak_pct=([0-9.]+)")


def log(msg: str) -> None:
    """Progress to stderr: stdout must stay one clean JSON line, and when the
    bench dies the driver's captured tail must say which stage died."""
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _plateaued(samples: list[float], rel_tol: float) -> bool:
    """True once the last THREE samples agree pairwise within ``rel_tol``
    — the warm-up ramp (compile, device/tunnel paging, cache fill) is over
    and further runs would only re-measure the same steady state. Three,
    not two: the r4 driver ramp (3.7, 15.8, 19.0, 19.1, ... → 45) has a
    two-sample flat spot at 19.0→19.1 mid-climb that a last-two rule
    would mistake for the plateau — exactly the understatement this
    heuristic exists to prevent."""
    if len(samples) < 3:
        return False
    tail = samples[-3:]
    hi = max(abs(s) for s in tail)
    return hi > 0 and (max(tail) - min(tail)) / hi <= rel_tol


async def run_gflops(
    dispatch: bool,
    runs: int,
    tmp: Path,
    *,
    adaptive: bool = False,
    max_runs: int = 12,
    plateau_rel_tol: float = 0.05,
    budget_s: float | None = None,
) -> tuple[float, dict]:
    config = Config(
        file_storage_path=str(tmp / f"storage-{dispatch}"),
        local_sandbox_root=str(tmp / f"sb-{dispatch}"),
        executor_pod_queue_target_length=1,
        default_execution_timeout=600.0,
        jax_compilation_cache_dir=_JAX_CACHE_DIR,
    )
    backend = LocalSandboxBackend(
        config, warm_import_jax=dispatch, numpy_dispatch=dispatch
    )
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log(f"filling pool (dispatch={dispatch})...")
        await executor.fill_pool()
        samples: list[float] = []
        single_shots: list[float] = []
        info: dict = {}
        # Adaptive sampling (VERDICT r4 #2): a fixed sample count understated
        # the chip by >2x when a run landed in a slow-tunnel window (driver
        # r4 samples 3.7 → 15.8 → 19.0 → 19.1 GFLOPS, still climbing at the
        # cutoff, vs 45.2 on identical code in r3). Keep sampling until the
        # last two steady-state samples agree within plateau_rel_tol or the
        # leg budget expires — `runs` becomes the MINIMUM sample count.
        leg_start = time.perf_counter()
        # Snapshot the budget ONCE: _remaining_s() shrinks as the leg
        # runs, so re-reading it inside the loop would double-count
        # elapsed time and stop the leg at roughly half its allowance.
        leg_budget = budget_s if budget_s is not None else _remaining_s()
        i = 0
        while True:
            if i >= runs:
                if not adaptive or i >= max_runs:
                    break
                if _plateaued(samples[1:], plateau_rel_tol):
                    log(f"plateau after {i} runs (dispatch={dispatch})")
                    break
                spent = time.perf_counter() - leg_start
                per_run = spent / max(i, 1)
                if spent + per_run * 1.5 > leg_budget:
                    log(f"leg budget reached after {i} runs (still climbing)")
                    break
            log(f"run {i} (dispatch={dispatch})...")
            t0 = time.perf_counter()
            result = await executor.execute(BENCH_SOURCE, timeout=600.0)
            elapsed = time.perf_counter() - t0
            if result.exit_code != 0:
                raise RuntimeError(f"bench execute failed: {result.stderr[-800:]}")
            match = GFLOPS_RE.search(result.stdout)
            if not match:
                raise RuntimeError(f"no GFLOPS line in: {result.stdout[-400:]}")
            gflops = float(match.group(1))
            single = SINGLE_SHOT_RE.search(result.stdout)
            if single:
                single_shots.append(float(single.group(1)))
            backend_line = next(
                (l for l in result.stdout.splitlines() if l.startswith("backend:")),
                "backend: ?",
            )
            info = {
                "run": i,
                "execute_wall_s": round(elapsed, 3),
                "array_type": backend_line.split(":", 1)[1].strip(),
                "phases": {
                    k: round(v, 4) if isinstance(v, (int, float)) else v
                    for k, v in result.phases.items()
                },
            }
            log(f"run {i}: {gflops:.3f} GFLOPS ({info['array_type']})")
            samples.append(gflops)
            i += 1
        # Run 0 includes first-compile; steady state = the rest (SURVEY §6 /
        # VERDICT r2 #3: N>=3, report best and median excluding compile).
        steady = samples[1:] if len(samples) > 1 else samples
        info["gflops_samples"] = [round(s, 3) for s in samples]
        info["gflops_median"] = round(statistics.median(steady), 3)
        if adaptive:
            info["gflops_plateaued"] = _plateaued(steady, plateau_rel_tol)
        if single_shots:
            info["gflops_single_shot_best"] = round(max(single_shots), 3)
        return max(steady), info
    finally:
        await executor.close()


async def run_matmul(tmp: Path) -> dict:
    """Compute-bound config: chained bf16 matmuls (pure JAX user code via
    Execute). Reports achieved TFLOPS + MFU vs v5e bf16 peak."""
    config = Config(
        file_storage_path=str(tmp / "storage-mm"),
        local_sandbox_root=str(tmp / "sb-mm"),
        executor_pod_queue_target_length=1,
        default_execution_timeout=600.0,
        jax_compilation_cache_dir=_JAX_CACHE_DIR,
    )
    # numpy_dispatch puts the repo on the sandbox path — the attention bench
    # imports the framework's Pallas kernel; matmul is pure jax either way.
    backend = LocalSandboxBackend(config, warm_import_jax=True, numpy_dispatch=True)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log("matmul: filling pool...")
        await executor.fill_pool()
        best: dict = {}
        for i in range(2):
            log(f"matmul run {i}...")
            result = await executor.execute(MATMUL_SOURCE, timeout=600.0)
            if result.exit_code != 0:
                raise RuntimeError(f"matmul execute failed: {result.stderr[-800:]}")
            tflops_m = TFLOPS_RE.search(result.stdout)
            if not tflops_m:
                raise RuntimeError(f"no TFLOPS line in: {result.stdout[-400:]}")
            tflops = float(tflops_m.group(1))
            mfu_m = MFU_RE.search(result.stdout)
            log(f"matmul run {i}: {tflops:.2f} TFLOPS")
            if not best or tflops > best["matmul_tflops"]:
                best = {
                    "matmul_tflops": tflops,
                    "matmul_mfu_vs_v5e_peak_pct": (
                        float(mfu_m.group(1)) if mfu_m else None
                    ),
                }
        # Long-context fused attention (Pallas flash kernel) through Execute.
        log("flash attention (t=16384)...")
        result = await executor.execute(ATTENTION_SOURCE, timeout=600.0)
        if result.exit_code == 0:
            attn = ATTN_RE.search(result.stdout)
            if attn:
                best["flash_attention_16k_tflops"] = float(attn.group(1))
                log(f"flash attention: {attn.group(1)} TFLOPS causal")
        else:
            log(f"flash attention failed (non-fatal): {result.stderr[-300:]}")
        return best
    finally:
        await executor.close()


async def _best_effort_leg(name: str, source: str, tmp: Path,
                           parse: tuple) -> None:
    """Shared body of the trailing best-effort legs (int8 decode ratio,
    serving-engine throughput): its own pool, a deadline-clamped execute,
    parse whatever reached stdout — both source scripts flush each marker
    AS IT IS MEASURED, so a timeout kill still leaves every completed
    number parseable — and a teardown that never raises. A failure or a
    skip never costs the already-measured legs.

    The deadline check runs BEFORE any pool fill: a cold fill with
    warm_import_jax can burn minutes, and paying it for a leg that is
    about to skip would steal time from nothing."""
    executor = None
    try:
        # No artificial floor: a timeout may never outlive the backstop
        # (which would clobber the measured headline with a deadline
        # error). 120 s execute minimum + 60 s margin.
        if _remaining_s() - 60.0 < 120.0:
            log(f"skipping {name} leg (deadline too near)")
            return
        config = Config(
            file_storage_path=str(tmp / f"storage-{name}"),
            local_sandbox_root=str(tmp / f"sb-{name}"),
            executor_pod_queue_target_length=1,
            default_execution_timeout=900.0,
            max_execution_timeout=1200.0,
            jax_compilation_cache_dir=_JAX_CACHE_DIR,
        )
        backend = LocalSandboxBackend(
            config, warm_import_jax=True, numpy_dispatch=True
        )
        executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
        log(f"{name}: filling pool...")
        await executor.fill_pool()
        timeout = min(_remaining_s() - 60.0, 900.0)
        if timeout < 120.0:
            log(f"skipping {name} execute (deadline too near)")
            return
        result = await executor.execute(source, timeout=timeout)
        found = 0
        for key, rx in parse:
            match = rx.search(result.stdout or "")
            if match:
                PARTIAL[key] = float(match.group(1))
                found += 1
        if result.exit_code != 0 and not found:
            log(f"{name} leg failed (non-fatal): {result.stderr[-300:]}")
            return
        log(f"{name} leg: parsed {found}/{len(parse)} metrics")
    except Exception as e:  # noqa: BLE001 — best-effort leg
        log(f"{name} leg failed (non-fatal): {e}")
    finally:
        if executor is not None:
            try:
                await executor.close()
            except Exception as e:  # noqa: BLE001 — still best-effort
                log(f"{name} leg teardown failed (non-fatal): {e}")


async def run_quant(tmp: Path) -> None:
    """int8 vs bf16 fused greedy decode through Execute — the weight-HBM
    ratio models/quant.py exists for, in the DRIVER's artifact rather
    than only a self-measured one."""
    await _best_effort_leg("int8", QUANT_SOURCE, tmp, (
        ("int8_decode_speedup", INT8_SPEEDUP_RE),
        ("int8_decode_tok_s", INT8_TOKS_RE),
        ("bf16_decode_tok_s", BF16_TOKS_RE),
    ))


async def run_serving(tmp: Path) -> None:
    """Continuous-batching engine throughput through Execute (config 5g's
    driver-artifact counterpart): dense + paged engine aggregate tok/s and
    the batching speedup over sequential decode."""
    await _best_effort_leg("serving", SERVING_SOURCE, tmp, (
        ("serving_engine_tok_s", ENGINE_TOKS_RE),
        ("serving_paged_tok_s", PAGED_TOKS_RE),
        ("serving_engine_speedup", ENGINE_SPEEDUP_RE),
    ))


async def cold_start_p50(tmp: Path, samples: int = 5, warm_jax: bool = True) -> float:
    """Execute RPC latency with a warm pool (the p50 the user sees).

    warm_jax=False keeps the sandboxes off the accelerator entirely — the
    degraded (wedged-chip) path still measures orchestration latency."""
    config = Config(
        file_storage_path=str(tmp / "storage-lat"),
        local_sandbox_root=str(tmp / "sb-lat"),
        executor_pod_queue_target_length=2,
        jax_compilation_cache_dir=_JAX_CACHE_DIR,
    )
    backend = LocalSandboxBackend(config, warm_import_jax=warm_jax, numpy_dispatch=warm_jax)
    executor = CodeExecutor(backend, Storage(config.file_storage_path), config)
    try:
        log("p50: filling pool...")
        await executor.fill_pool()
        latencies = []
        for i in range(samples):
            t0 = time.perf_counter()
            result = await executor.execute("print(21 * 2)")
            latencies.append(time.perf_counter() - t0)
            assert result.exit_code == 0
            log(f"p50 sample {i}: {latencies[-1]:.3f}s")
            # let the refill task restore the pool before the next sample
            await executor.fill_pool()
        return statistics.median(latencies)
    finally:
        await executor.close()


def prime_accelerator(budget_s: float) -> tuple[bool, str]:
    """One clean-exiting subprocess that imports jax and touches the devices
    BEFORE any sandbox spawns. First-ever TPU init on a cold host pages in
    the whole jax/libtpu stack and establishes the device session — so it
    gets its own budget here, in a process that is NEVER killed (killing a
    client mid-init is exactly what wedges the shared device for the next
    30+ minutes). Two terminal outcomes short of success:

    - the child exits rc!=0 (e.g. UNAVAILABLE: an earlier client's stale
      claim still holds the chip) → terminal, degrade immediately;
    - the child outlives ``budget_s`` (attach is hanging on a wedged chip)
      → leave it running as an orphan to finish attaching on its own —
      its eventual clean exit is what lets the device recover — and
      degrade without it.

    Round 3's driver artifact came back empty because this stage only
    *logged* rc=1 and the bench walked on into pool fills that blocked on
    the same dead chip. Now a failed prime is terminal."""
    import subprocess
    import tempfile

    log(f"priming accelerator (budget {budget_s:.0f}s, child never killed)...")
    t0 = time.perf_counter()
    # Child output goes to a real file, not a pipe: a wedged-chip child can
    # emit retry warnings past a pipe buffer and block in write(), and an
    # orphaned child must never die of BrokenPipeError mid-attach.
    outf = tempfile.NamedTemporaryFile(
        mode="w+", prefix="bench-prime-", suffix=".log", delete=False
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import jax, jax.numpy as jnp;"
            "print(jax.devices());"
            "jnp.add(jnp.ones(()), 1.0).block_until_ready()",
        ],
        stdout=outf,
        stderr=subprocess.STDOUT,
    )
    try:
        rc = proc.wait(timeout=budget_s)
    except subprocess.TimeoutExpired:
        # Do NOT kill it: orphan the child so its attach can complete
        # (and release the device cleanly) long after we've moved on. It
        # keeps its inherited file descriptor; we just stop watching.
        outf.close()
        log(
            f"prime exceeded {budget_s:.0f}s budget; leaving child "
            f"pid={proc.pid} to finish on its own (log: {outf.name}), "
            f"declaring the accelerator unavailable for this run"
        )
        return False, (
            f"accelerator attach exceeded {budget_s:.0f}s budget "
            f"(device wedged by a stale claim?); primer orphaned, not killed"
        )
    outf.seek(0)
    out = outf.read().strip()
    outf.close()
    tail = out.splitlines()[-1:] if out else []
    dt = time.perf_counter() - t0
    log(f"prime done in {dt:.1f}s rc={rc} {tail}")
    if rc != 0:
        return False, f"accelerator init failed rc={rc}: {tail}"
    PARTIAL["prime_s"] = round(dt, 1)
    return True, f"prime ok in {dt:.1f}s"


def _last_self_artifact() -> dict:
    """Pointer to the newest self-measured artifact so a degraded driver
    line still references the last healthy-chip numbers."""
    cands = sorted(REPO_ROOT.glob("BENCH_r[0-9]*_self.json"))
    if not cands:
        return {}
    out: dict = {"last_self_measured_artifact": cands[-1].name}
    try:
        data = json.loads(cands[-1].read_text())
        headline = data.get("headline", {})
        if "value" in headline:
            out["last_self_measured_headline_gflops"] = headline["value"]
    except (OSError, ValueError):
        pass
    return out


def _remaining_s(default: float = 600.0) -> float:
    """Seconds left before the overall deadline (with a safety margin), so
    inner leg timeouts never outlive the backstop that would clobber the
    specific error message with a generic deadline one."""
    if _DEADLINE_AT is None:
        return default
    return max(_DEADLINE_AT - time.perf_counter() - 45.0, 30.0)


async def degraded_cpu_bench(tmp: Path) -> None:
    """The accelerator is unusable: measure everything that doesn't need it
    (CPU-sandbox numpy baseline + warm-pool Execute p50 with jax kept out of
    the sandboxes) so the driver's artifact still lands real numbers."""
    log("degraded mode: CPU-sandbox legs only")
    try:
        cpu_gflops, cpu_info = await asyncio.wait_for(
            run_gflops(dispatch=False, runs=2, tmp=tmp),
            timeout=min(420.0, _remaining_s() * 0.6),
        )
        PARTIAL["cpu_numpy_gflops"] = round(cpu_gflops, 3)
        PARTIAL["cpu_run"] = cpu_info
    except Exception as e:  # noqa: BLE001 — degraded mode reports what it can
        log(f"degraded cpu gflops leg failed: {e}")
    try:
        p50 = await asyncio.wait_for(
            cold_start_p50(tmp, warm_jax=False),
            timeout=min(240.0, _remaining_s()),
        )
        PARTIAL["execute_p50_warm_pool_s_cpu_sandbox"] = round(p50, 4)
    except Exception as e:  # noqa: BLE001
        log(f"degraded p50 leg failed: {e}")


async def main(prime_ok: bool, prime_detail: str) -> None:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench-") as tmp_str:
        tmp = Path(tmp_str)
        if not prime_ok:
            await degraded_cpu_bench(tmp)
            _emit_error(f"accelerator unavailable: {prime_detail}")
            sys.exit(1)
        # Adaptive: at least 4 samples, then keep going until the steady
        # state plateaus (or ~40% of the remaining deadline is spent) so a
        # slow-tunnel warm-up window can't understate the chip.
        tpu_gflops, tpu_info = await run_gflops(
            dispatch=True,
            runs=4,
            tmp=tmp,
            adaptive=True,
            budget_s=_remaining_s() * 0.4,
        )
        PARTIAL["tpu_gflops"] = round(tpu_gflops, 3)
        PARTIAL["tpu_run"] = tpu_info
        matmul = await run_matmul(tmp)
        PARTIAL.update(matmul)
        cpu_gflops, _ = await run_gflops(dispatch=False, runs=1, tmp=tmp)
        PARTIAL["cpu_numpy_gflops"] = round(cpu_gflops, 3)
        p50 = await cold_start_p50(tmp)
        PARTIAL["execute_p50_warm_pool_s"] = round(p50, 4)
        if _remaining_s() > 300.0:
            # run_quant guards itself, but the headline must survive even a
            # bug in that guard — belt and braces for the last legs.
            try:
                await run_quant(tmp)
            except Exception as e:  # noqa: BLE001
                log(f"int8 leg failed (non-fatal): {e}")
        else:
            log("skipping int8 leg (deadline near)")
        if _remaining_s() > 300.0:
            try:
                await run_serving(tmp)
            except Exception as e:  # noqa: BLE001
                log(f"serving leg failed (non-fatal): {e}")
        else:
            log("skipping serving leg (deadline near)")

    line = {
        "metric": METRIC,
        "value": round(tpu_gflops, 3),
        "unit": "GFLOPS",
        "vs_baseline": round(tpu_gflops / cpu_gflops, 2) if cpu_gflops else None,
        "extra": dict(PARTIAL),
    }
    print(json.dumps(line))


def _emit_error(kind: str) -> None:
    """The degraded stdout contract: still exactly one parseable JSON line,
    with an `error` field instead of a headline measurement — but carrying
    every leg measured before the failure (PARTIAL) plus a pointer to the
    last healthy-chip self-measured artifact."""
    log(f"bench failed: {kind}")
    # Snapshot defensively: the backstop timer thread calls this while the
    # event-loop thread may be mutating PARTIAL.
    try:
        extra = {**dict(PARTIAL), **_last_self_artifact()}
    except RuntimeError:
        extra = _last_self_artifact()
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "GFLOPS",
                "vs_baseline": None,
                "error": kind[:500],
                "extra": extra,
            }
        ),
        flush=True,
    )


def _run_with_deadline() -> None:
    """Run the bench under an overall deadline, degrading to a parseable
    JSON error line instead of hanging or crashing with a bare traceback.

    The failure this guards: a test-rig device wedged by some earlier
    client killed mid-init makes every TPU attach hang. Round 3 showed the
    original guard was not enough — the primer alone burned 1508 s of a
    2700 s deadline and the DRIVER's window expired before the backstop
    fired, so the round's official artifact recorded nothing. Hence:

    - default deadline 1200 s, well under any sane driver window;
    - the primer gets its own sub-budget (BENCH_PRIME_BUDGET_S, 420 s) and
      a failed/overrun prime is TERMINAL → degraded CPU-only legs + one
      structured error line, never a march into wedged pool fills;
    - the backstop thread emits whatever PARTIAL results exist and
      os._exit()s, which works even while the event loop is blocked."""
    try:
        deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "") or 1200)
    except ValueError:
        deadline_s = 1200.0
    try:
        prime_budget_s = float(os.environ.get("BENCH_PRIME_BUDGET_S", "") or 420)
    except ValueError:
        prime_budget_s = 420.0
    prime_budget_s = min(prime_budget_s, deadline_s * 0.5)
    deadline_msg = f"deadline of {deadline_s:.0f}s exceeded (accelerator hung?)"

    # Thread backstop: pool fills / executes can block the event loop on a
    # wedged chip in ways asyncio.wait_for cannot preempt. The timer emits
    # the error line (with any PARTIAL results) and exits the bench; any
    # orphaned primer child is left to finish on its own (never killed
    # mid-init — killing a client mid-TPU-init is what wedges devices).
    import threading

    start = time.perf_counter()
    global _DEADLINE_AT
    _DEADLINE_AT = start + deadline_s

    def _hard_deadline() -> None:
        # Whatever happens while formatting, the process MUST exit here —
        # a dead backstop is how an artifact comes back empty.
        try:
            _emit_error(deadline_msg)
        finally:
            os._exit(1)

    timer = threading.Timer(deadline_s, _hard_deadline)
    timer.daemon = True
    timer.start()
    prime_ok, prime_detail = prime_accelerator(prime_budget_s)
    remaining = max(deadline_s - (time.perf_counter() - start) - 30.0, 60.0)
    try:
        asyncio.run(asyncio.wait_for(main(prime_ok, prime_detail), timeout=remaining))
        timer.cancel()
    except SystemExit:
        timer.cancel()
        raise
    except Exception as e:  # noqa: BLE001 — the output contract is one JSON line
        # Cancel BEFORE emitting: teardown of wedged sandboxes can take long
        # enough that the backstop would otherwise fire concurrently and put
        # a second JSON line on stdout.
        timer.cancel()
        if isinstance(e, (asyncio.TimeoutError, TimeoutError)):
            _emit_error(deadline_msg)
        else:
            _emit_error(f"{type(e).__name__}: {e}")
        sys.exit(1)


if __name__ == "__main__":
    _run_with_deadline()
