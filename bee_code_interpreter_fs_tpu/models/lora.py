"""LoRA / QLoRA adapters — parameter-efficient fine-tuning on TPU.

The reference project has no fine-tuning story at all (its sandbox runs
user-supplied torch/CUDA scripts; nothing in
`/root/reference/src` or `/root/reference/executor` trains); this module is
part of the TPU framework surface that replaces it. Design:

- An adapted weight is a COMPOSITE LEAF ``{"base", "lora_a", "lora_b"}``
  in the same stacked-[n_layers, ...] layout the layer `lax.scan` consumes.
  The model's single matmul-weight accessor (`llama._w`) materializes
  ``base + a @ b`` at the use site inside the scan, so every existing code
  path — forward, fused generate, speculative decode, the continuous-
  batching engine, pipeline stages — serves adapted weights with ZERO
  changes: `lora_wrap` produces a params tree that drops in anywhere a
  params tree goes.
- ``base`` may itself be an int8 ``{"q","s"}`` or packed-int4
  ``{"q4","s4"}`` leaf (models/quant.py): that composition IS QLoRA — the
  frozen base streams from HBM at 1 or 0.5 bytes/param while the trainable
  adapters stay in float32. Nothing special-cases it; `_w` recurses.
- Training optimizes ONLY the adapter tree: `make_lora_train_step` closes
  over the frozen base, so jax.grad never touches it, the optimizer state
  is adapter-sized (rank × dims, thousands of times smaller than the
  model), and the base can stay quantized the whole time.

TPU notes: the rank-r update adds two skinny matmuls per adapted weight
per step (in×r, r×out) — XLA fuses the cast/scale chain; at serving time
`merge_lora` folds the update into dense weights so inference pays zero
adapter cost (quantized bases serve wrapped instead — merging would
dequantize).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bee_code_interpreter_fs_tpu.models.llama import LlamaConfig
from bee_code_interpreter_fs_tpu.models.quant import is_quantized, is_quantized4

__all__ = [
    "DEFAULT_TARGETS",
    "init_lora",
    "lora_wrap",
    "lora_param_specs",
    "merge_lora",
    "make_lora_train_step",
    "is_lora_leaf",
    "stack_loras",
    "multi_lora_wrap",
    "zero_lora",
]

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")

# target -> (in_dim, out_dim) as functions of the config
def _target_dims(cfg: LlamaConfig, name: str) -> tuple[int, int]:
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    dims = {
        "wq": (cfg.dim, nh * hd),
        "wk": (cfg.dim, nkv * hd),
        "wv": (cfg.dim, nkv * hd),
        "wo": (nh * hd, cfg.dim),
    }
    if cfg.n_experts == 0:
        dims.update({
            "w_gate": (cfg.dim, cfg.hidden_dim),
            "w_up": (cfg.dim, cfg.hidden_dim),
            "w_down": (cfg.hidden_dim, cfg.dim),
        })
    if name not in dims:
        extra = (
            " (MoE expert MLPs are not adaptable: their stacked [E, ...] "
            "weights would need per-expert adapters)"
            if cfg.n_experts > 0 and name in ("w_gate", "w_up", "w_down")
            else ""
        )
        raise ValueError(f"unknown LoRA target {name!r}{extra}")
    return dims[name]


def is_lora_leaf(leaf) -> bool:
    return isinstance(leaf, dict) and "lora_a" in leaf


def init_lora(key, cfg: LlamaConfig, *, rank: int = 8,
              targets: tuple = DEFAULT_TARGETS):
    """Adapter tree {"layers": {target: {"a": [L, in, r], "b": [L, r, out]}}}.

    `a` gets a fan-in-scaled normal init, `b` starts at ZERO — the wrapped
    model is exactly the base model at step 0 (the standard LoRA identity
    init, so fine-tuning departs smoothly from the pretrained function).
    Adapters are float32 regardless of cfg.dtype: they are tiny, and they
    are the only thing the optimizer touches.
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    L = cfg.n_layers
    out = {}
    for name in targets:
        d_in, d_out = _target_dims(cfg, name)
        key, k = jax.random.split(key)
        out[name] = {
            "a": jax.random.normal(k, (L, d_in, rank), jnp.float32)
            * d_in ** -0.5,
            "b": jnp.zeros((L, rank, d_out), jnp.float32),
        }
    return {"layers": out}


def lora_wrap(params, lora, *, alpha: float = 16.0):
    """Attach adapters: returns a params tree whose target leaves are
    composite ``{"base", "lora_a", "lora_b"}`` dicts that `llama._w`
    resolves to ``base + a @ b`` at every use site. The alpha/rank scale is
    folded into lora_b here (one cheap [L, r, out] multiply under jit).
    Works on dense AND quantized bases (QLoRA); cheap enough to call inside
    the train step every iteration.
    """
    layers = dict(params["layers"])
    for name, ab in lora["layers"].items():
        rank = ab["a"].shape[-1]
        layers[name] = {
            "base": params["layers"][name],
            "lora_a": ab["a"],
            "lora_b": ab["b"] * (alpha / rank),
        }
    return {**params, "layers": layers}


def lora_param_specs(cfg: LlamaConfig, *, targets: tuple = DEFAULT_TARGETS,
                     base_specs=None):
    """PartitionSpec tree matching a `lora_wrap` tree — the analog of
    quant.quantized_param_specs for the LoRA structural leaf change, so
    explicitly-sharded paths (device_put / jit in_shardings built from
    specs) keep working on adapted trees.

    Target leaves become {"base": <base spec>, "lora_a", "lora_b"}:
    `lora_a` shards its input dim like the base weight's input dim and
    `lora_b` its output dim like the base's output dim (the rank dim
    replicates) — under tp the skinny adapter matmuls then compose with
    the base matmul's existing collective placement instead of adding one.
    `base_specs` defaults to `llama.param_specs(cfg)`; pass
    quantized(4)_param_specs output for a QLoRA tree.
    """
    from bee_code_interpreter_fs_tpu.models.llama import param_specs

    base_specs = base_specs if base_specs is not None else param_specs(cfg)
    P = jax.sharding.PartitionSpec
    layers = dict(base_specs["layers"])
    for name in targets:
        _target_dims(cfg, name)  # validates the target for this config
        spec = layers[name]
        if isinstance(spec, dict):  # quantized base: {"q": P, "s": P}
            ref = spec["q" if "q" in spec else "q4"]
        else:
            ref = spec
        in_s, out_s = ref[1], ref[2]
        layers[name] = {
            "base": spec,
            "lora_a": P(None, in_s, None),
            "lora_b": P(None, None, out_s),
        }
    return {**base_specs, "layers": layers}


def merge_lora(params, lora, *, alpha: float = 16.0):
    """Fold adapters into the dense base weights (serving: zero adapter
    cost). Quantized bases refuse — merging would silently dequantize the
    model; serve the `lora_wrap` tree instead, which keeps the base at
    1/0.5 bytes/param and adds only the two skinny matmuls."""
    layers = dict(params["layers"])
    for name, ab in lora["layers"].items():
        base = params["layers"][name]
        if is_quantized(base) or is_quantized4(base):
            raise ValueError(
                f"cannot merge LoRA into quantized base {name!r}; serve the "
                "lora_wrap tree instead"
            )
        rank = ab["a"].shape[-1]
        delta = jnp.einsum("lir,lro->lio", ab["a"], ab["b"]) * (alpha / rank)
        layers[name] = (base.astype(jnp.float32) + delta).astype(base.dtype)
    return {**params, "layers": layers}


def stack_loras(loras, *, targets: tuple = DEFAULT_TARGETS,
                alpha: float = 16.0):
    """Stack N adapter trees (same rank/targets) for multi-adapter serving:
    {"layers": {t: {"a": [L, N, in, r], "b": [L, N, r, out]}}} — adapter
    index is axis 1 so the layer `lax.scan` still slices axis 0. The
    alpha/rank scale is folded into the b-stack HERE, once — wrapping per
    burst must stay allocation-free over the bank."""
    if not loras:
        raise ValueError("need at least one adapter")
    out = {}
    for name in targets:
        abs_ = [lo["layers"][name] for lo in loras]
        ranks = {ab["a"].shape[-1] for ab in abs_}
        if len(ranks) != 1:
            raise ValueError(
                f"adapters disagree on rank for {name!r}: {sorted(ranks)}"
            )
        rank = next(iter(ranks))
        out[name] = {
            "a": jnp.stack([ab["a"] for ab in abs_], axis=1),
            "b": jnp.stack([ab["b"] for ab in abs_], axis=1)
            * (alpha / rank),
        }
    return {"layers": out}


def multi_lora_wrap(params, stacked, ids):
    """Attach a STACK of adapters with a per-batch-row selection: target
    leaves become {"base", "lora_a_stack" [L, N, in, r], "lora_b_stack",
    "lora_ids" [L, b]} and `llama._mm` applies row i's adapter ids[i]
    activation-side (batched gather + two skinny bmms). `ids` is [b] and is
    broadcast with a leading layer axis only so it can ride the layer scan
    beside the weights; pass it as a traced array — changing the selection
    never recompiles. Cheap enough for every burst: it only rebuilds leaf
    dicts around the SAME arrays (stack_loras already folded the
    alpha/rank scale in). The serving engines use this to serve MANY
    fine-tunes from one resident base model (multi-tenant adapter
    serving)."""
    layers = dict(params["layers"])
    ids = jnp.asarray(ids, jnp.int32)
    L = next(iter(stacked["layers"].values()))["a"].shape[0]
    ids_l = jnp.broadcast_to(ids[None, :], (L, ids.shape[0]))
    for name, ab in stacked["layers"].items():
        layers[name] = {
            "base": params["layers"][name],
            "lora_a_stack": ab["a"],
            "lora_b_stack": ab["b"],
            "lora_ids": ids_l,
        }
    return {**params, "layers": layers}


def zero_lora(cfg: LlamaConfig, *, rank: int = 8,
              targets: tuple = DEFAULT_TARGETS):
    """The identity adapter (all-zero a and b): multi-adapter stacks put it
    at index 0 so un-adapted requests select it and get the exact base
    model."""
    lora = init_lora(jax.random.PRNGKey(0), cfg, rank=rank, targets=targets)
    return jax.tree.map(jnp.zeros_like, lora)


def make_lora_train_step(cfg: LlamaConfig, optimizer, base_params, *,
                         alpha: float = 16.0, mesh=None):
    """Returns jittable `step(lora, opt_state, batch) -> (lora, opt_state,
    loss)` that trains ONLY the adapters against the frozen (possibly
    quantized — QLoRA) base. Mirrors `llama.make_train_step`'s contract;
    the optimizer state is adapter-sized."""
    from bee_code_interpreter_fs_tpu.models.llama import loss_fn

    def adapter_loss(lora, batch):
        return loss_fn(lora_wrap(base_params, lora, alpha=alpha), batch, cfg,
                       mesh=mesh)

    def step(lora, opt_state, batch):
        loss, grads = jax.value_and_grad(adapter_loss)(lora, batch)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = jax.tree.map(lambda p, u: p + u.astype(p.dtype), lora, updates)
        return lora, opt_state, loss

    return step
