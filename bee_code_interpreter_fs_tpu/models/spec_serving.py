"""Speculative decoding composed INTO the continuous-batching engine.

The framework's two best decode accelerators could not previously be used
together: `speculative_generate` (llama.py) is a whole-generation,
batch-lockstep program, and `ServingEngine` (serving.py) decodes one token
per slot per step. This module puts a draft/verify loop inside the burst
body, per SLOT — the shape production TPU servers use:

- Every burst pass, a cheap DRAFT model proposes γ tokens per slot
  autoregressively (γ+1 fused per-slot decode steps), then the TARGET
  scores all proposals in ONE per-slot chunked forward
  (`_perslot_decode_chunk`): up to γ+1 target tokens per slot per pass
  instead of 1.
- Unlike the lockstep generator, slots accept INDEPENDENTLY — the slot
  bank's per-slot position vector already carries ragged progress, so a
  slot that agreed γ deep advances γ+1 while its neighbor advances 1.
- Greedy acceptance = token equality, so the emitted stream is EXACTLY
  the non-speculative engine's (token-exact; the draft only decides how
  many target tokens a pass yields, never what they are).
- Sampled requests (temperature > 0) run the full accept/resample
  speculative-sampling algorithm per slot — distribution-exact vs
  ancestral sampling from the target (the engine-level counterpart of
  llama.speculative_sample_generate), sharing bursts with greedy slots.

The win is at LOW slot occupancy: decode at small active-batch is
weight-HBM-bound, so γ draft steps (a model 10-30x smaller) plus one
γ+1-token target pass reads the big weight tree once where plain decode
reads it γ+1 times. At high occupancy the target pass is already
compute-dense and speculation's edge shrinks — measure before deploying
(examples/benchmark-serving-spec.py).

Cache-consistency invariant (same overwrite-before-read rule the dense
engine relies on): the verify chunk writes K/V for positions
pos..pos+γ; positions past the acceptance point hold K/V of REJECTED
draft tokens, but the next pass's chunk starts at pos' <= pos+accept+1
and rewrites every such position before any query can attend it (a query
at q only sees keys <= q, and key q is rewritten by the chunk covering it
before the first query with q' >= q runs).

The TARGET cache may be int8 (`kv_quant=True`): the verify chunk routes
through the one shared quantize-at-write / dequantize-at-read recipe, so
long-context HBM savings and speculation compose; the DRAFT cache stays
dense (the draft is small — its cache is not the memory term that
matters). Prefix caching works on both sides: register_prefix prefills
the prefix through the draft too, so sharing requests skip the prefix
forward for BOTH models. v1 scope beyond that: top_p, logprobs,
penalties, and LoRA adapters are rejected at submit()/__init__ —
compose with the plain engine for those.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _cached_gqa_attention,
    _rms_norm,
    _w,
    decode_valid_mask,
    init_cache,
    transformer_block,
)
from bee_code_interpreter_fs_tpu.models.paged import (
    PagedServingEngine,
)
from bee_code_interpreter_fs_tpu.models.serving import (
    Request,
    ServingEngine,
    _admit,
    _admit_prefix_only,
    _admit_prefixed,
    _chunked_scratch_prefill,
    _install_row,
    _kv_write_read,
    _perslot_decode_step,
    _prefix_prefill,
)

__all__ = ["PagedSpeculativeServingEngine", "SpeculativeServingEngine"]


def _perslot_decode_chunk(params, tokens, cache, pos, cfg: LlamaConfig):
    """Chunked decode where every slot's chunk starts at its OWN position:
    tokens [b, s] with slot i's token j at global position pos[i]+j — the
    s>1 generalization of serving._perslot_decode_step (vector RoPE
    offsets, per-slot-per-query causal masks, per-slot chunk scatters).
    Returns (logits [b, s, vocab] f32 for all s positions, updated cache).
    This is the serving engine's speculative VERIFY pass. An int8 cache
    ("kq" present, engine kv_quant=True) routes through the one shared
    quantize-at-write / dequantize-at-read recipe (_kv_write_read) — the
    same per-vector granularity as the plain engine's decode step, so
    spec+int8 stays token-exact vs plain+int8."""
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    quant = "kq" in cache
    b, s = tokens.shape
    max_len = (cache["kq"] if quant else cache["k"]).shape[2]
    qpos = pos[:, None] + jnp.arange(s)[None, :]  # [b, s]
    # Slot i's query j sees cache positions <= pos[i]+j (window/sinks via
    # the one shared visibility formula).
    valid = decode_valid_mask(qpos.reshape(-1), max_len, cfg).reshape(
        b, s, max_len
    )[:, None, None, :, :]
    x = params["embed"].astype(dt)[tokens]
    bidx = jnp.arange(b)

    # Per-slot scatter of the whole chunk at each slot's frontier
    # (out-of-bounds rows of an inactive slot's stale qpos drop).
    cache_keys, write_read = _kv_write_read(
        quant, lambda c, x: c.at[bidx[:, None], qpos].set(x),
        lambda c: c, dt,
    )

    def layer(x, inputs):
        lp = inputs[0]
        cs = inputs[1:]
        cell = {}

        def attn_fn(q, k, v):
            new, keys_r, vals_r = write_read(cs, k, v)
            cell["kv"] = new
            return _cached_gqa_attention(q, keys_r, vals_r, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    x, new_leaves = lax.scan(
        layer, x, (params["layers"],) + tuple(cache[k] for k in cache_keys)
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, dict(zip(cache_keys, new_leaves))


def _fold2(keys, data, tag: int):
    """Per-slot subkey for one decision site: fold_in(key, position) then
    fold_in(tag) — distinct tags give the draft draw, the accept test,
    and the boundary draw independent streams at the same position."""
    k2 = jax.vmap(jax.random.fold_in)(keys, data)
    return jax.vmap(jax.random.fold_in)(
        k2, jnp.full(data.shape, tag, jnp.uint32)
    )


def _spec_burst_scan(verify_fn, dparams, store, dcache, pos, last_tok,
                     remaining, active, temp, keys, dcfg: LlamaConfig,
                     steps: int, gamma: int, eos_id,
                     with_sampling: bool = False):
    """The ONE speculative burst loop both storage backends run —
    `verify_fn(store, chunk, pos, active)` is the only difference between
    the dense slot-bank and the paged block-pool engines (mirrors how
    serving._burst_scan is shared by the plain engines), so the
    draft/accept/resample/clamp logic cannot drift between them.

    `steps` draft/verify passes over the slot bank. Invariant at the top
    of each pass (per slot): `last_tok[i]` is the newest emitted token,
    sitting unfed at position pos[i]; both caches hold K/V for positions
    < pos[i]. Each pass emits 1..γ+1 tokens per active slot (clamped by
    budget and eos). Returns the updated carry plus
    (toks [steps, b, γ+1], emitted [steps, b, γ+1]) — pass-major emission
    order, so flattening the trailing axis reconstructs each slot's
    stream exactly.

    Greedy slots (temp == 0) accept by TOKEN EQUALITY — output exactly
    the plain engine's greedy stream. With `with_sampling` (static; only
    compiled when a sampled request occupies a slot), temp > 0 slots run
    the full accept/resample speculative-sampling algorithm per slot
    (Leviathan et al.): the draft PROPOSES d_j ~ q_j, position j accepts
    with prob min(1, p_j(d_j)/q_j(d_j)), and the first rejection
    resamples from normalize(relu(p_j - q_j)); all-accepted rows draw
    the bonus token from p_γ — which is exactly the γ-th residual once
    q_γ is defined as the zero vector, so one gather serves both cases.
    The emitted sequence is distribution-exact vs ancestral sampling
    from the target (empirically pinned in tests), though not
    stream-identical to the plain engine (different algorithm, different
    draw sites). Decisions key off fold_in(slot key, token position), so
    a seeded request reproduces regardless of batch composition."""
    b = pos.shape[0]
    bidx = jnp.arange(b)
    idx = jnp.arange(gamma + 1)

    def one(carry, _):
        store, dcache, pos, tok, remaining, active = carry

        # Draft rollout: γ+1 per-slot steps. Step j feeds the token at
        # position pos+j; steps 0..γ-1 yield proposals d_1..d_γ, the extra
        # step feeds d_γ so the draft cache covers pos+γ for the
        # all-accepted case (mirrors llama.speculative_generate's droll).
        def droll(c, j):
            t, dc = c
            logits, dc = _perslot_decode_step(
                dparams, t[:, None], dc, pos + j, dcfg
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if with_sampling:
                scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]
                q = jax.nn.softmax(scaled, axis=-1)  # [b, vocab] f32
                draw = jax.vmap(jax.random.categorical)(
                    _fold2(keys, pos + j + 1, 1), scaled
                ).astype(jnp.int32)
                nxt = jnp.where(temp > 0, draw, nxt)
            else:
                q = jnp.zeros((b, 1), jnp.float32)  # unused, shape-stable
            return (nxt, dc), (nxt, q)

        (_, dcache), (props, qs) = lax.scan(
            droll, (tok, dcache), jnp.arange(gamma + 1)
        )
        drafts = props[:gamma].T  # [b, γ]

        # Verify: target scores [pending, d_1..d_γ] at pos..pos+γ in one
        # per-slot chunk; t_preds[:, j] is the target's greedy choice for
        # position pos+j+1.
        chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
        v_logits, store = verify_fn(store, chunk, pos, active)
        t_preds = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)  # [b, γ+1]

        # Greedy acceptance: per-slot longest agreeing prefix — NO
        # batch-min lockstep: the slot bank's position vector carries
        # ragged progress natively.
        agree = drafts == t_preds[:, :gamma]
        row_accept = jnp.where(
            agree.all(axis=1), gamma,
            jnp.argmin(agree.astype(jnp.int32), axis=1),
        )
        out = t_preds

        if with_sampling:
            pt = jax.nn.softmax(
                v_logits / jnp.where(temp > 0, temp, 1.0)[:, None, None],
                axis=-1,
            )  # [b, γ+1, vocab]
            q_d = jnp.take_along_axis(
                jnp.transpose(qs[:gamma], (1, 0, 2)), drafts[..., None],
                axis=-1,
            )[..., 0]  # [b, γ] — q_j(d_j)
            p_d = jnp.take_along_axis(
                pt[:, :gamma], drafts[..., None], axis=-1
            )[..., 0]  # [b, γ] — p_j(d_j)
            u = jax.vmap(
                lambda k: jax.random.uniform(k, (gamma,)),
            )(_fold2(keys, pos, 2))  # per-pass accept draws
            acc = u * jnp.maximum(q_d, 1e-30) < p_d  # u < min(1, p/q)
            s_accept = jnp.where(
                acc.all(axis=1), gamma,
                jnp.argmin(acc.astype(jnp.int32), axis=1),
            )
            # Boundary distribution: residual at the rejection row, and
            # with q_γ := 0 the all-accepted case's bonus p_γ is the same
            # gather — append a zero row to q.
            qs_ext = jnp.concatenate(
                [jnp.transpose(qs[:gamma], (1, 0, 2)),
                 jnp.zeros_like(pt[:, :1])], axis=1,
            )  # [b, γ+1, vocab]
            p_b = jnp.take_along_axis(
                pt, s_accept[:, None, None], axis=1
            )[:, 0]
            q_b = jnp.take_along_axis(
                qs_ext, s_accept[:, None, None], axis=1
            )[:, 0]
            residual = jnp.maximum(p_b - q_b, 0.0)
            boundary = jax.vmap(jax.random.categorical)(
                _fold2(keys, pos + s_accept + 1, 3),
                jnp.log(residual + 1e-30),
            ).astype(jnp.int32)
            out_s = jnp.where(
                idx[None, :] < s_accept[:, None],
                jnp.pad(drafts, ((0, 0), (0, 1))),
                boundary[:, None],
            )
            sampled = temp > 0
            row_accept = jnp.where(sampled, s_accept, row_accept)
            out = jnp.where(sampled[:, None], out_s, out)

        emit_n = jnp.minimum(row_accept + 1, remaining)
        if eos_id is not None:
            # Stop at (and include) the first emitted eos.
            is_eos = (out == eos_id) & (idx[None] < emit_n[:, None])
            first_eos = jnp.where(
                is_eos.any(axis=1), jnp.argmax(is_eos, axis=1), gamma + 1
            )
            emit_n = jnp.minimum(emit_n, first_eos + 1)
        emit_n = jnp.where(active, emit_n, 0)
        emitted = idx[None, :] < emit_n[:, None]  # [b, γ+1]
        new_tok = jnp.where(
            active, out[bidx, jnp.maximum(emit_n - 1, 0)], tok
        )
        pos = pos + emit_n
        remaining = remaining - emit_n
        active = active & (remaining > 0)
        if eos_id is not None:
            active = active & (new_tok != eos_id)
        return (store, dcache, pos, new_tok, remaining, active), (
            out, emitted
        )

    carry, (toks, emitted) = lax.scan(
        one, (store, dcache, pos, last_tok, remaining, active),
        None, length=steps,
    )
    store, dcache, pos, tok, remaining, active = carry
    return store, dcache, pos, tok, remaining, active, toks, emitted


@partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "steps", "gamma", "eos_id",
                     "with_sampling"),
    donate_argnames=("cache", "dcache"),
)
def _spec_decode_burst(params, dparams, cache, dcache, pos, last_tok,
                       remaining, active, temp, keys, cfg: LlamaConfig,
                       dcfg: LlamaConfig, steps: int, gamma: int, eos_id,
                       with_sampling: bool = False):
    """Dense-cache speculative burst: verify against the [n_slots,
    max_len] slot bank (see _spec_burst_scan for the shared loop)."""

    def verify_fn(cache, chunk, pos, active):
        # Dense rows are slot-private: an inactive slot's stale-frontier
        # rewrite is harmless, so `active` is unused here.
        return _perslot_decode_chunk(params, chunk, cache, pos, cfg)

    return _spec_burst_scan(verify_fn, dparams, cache, dcache, pos,
                            last_tok, remaining, active, temp, keys, dcfg,
                            steps, gamma, eos_id, with_sampling)


def _perslot_decode_chunk_paged(params, tokens, pool, tables, pos, active,
                                limit, cfg: LlamaConfig):
    """The paged twin of _perslot_decode_chunk: a γ+1-token chunk per
    slot against the block pool, each slot at its own position. Writes
    land at (table[(pos+j)//bs], (pos+j)%bs); reads attend the gathered
    logical cache.

    The chunk can reach up to γ positions PAST a slot's real end (the
    rejected-proposal tail when `remaining` is nearly spent). In the
    dense engine those writes harmlessly rewrite the slot's own row; here
    a position beyond the slot's RESERVATION would write through a table
    row the slot does not own — another request's block. `limit` [b] is
    each slot's reserved token extent: writes at qpos >= limit (and all
    writes of inactive slots) divert to the pool's trash block. Every
    eventually-EMITTED position is < limit by construction
    (reservation covers prompt + max_new), so diverted writes are only
    ever rejected-tail garbage, rewritten through the real block by the
    pass whose chunk covers them."""
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    quant = "kq" in pool
    b, s = tokens.shape
    ref = pool["kq"] if quant else pool["k"]
    bs = ref.shape[2]
    trash = ref.shape[1] - 1
    max_blocks = tables.shape[1]
    logical = max_blocks * bs
    qpos = pos[:, None] + jnp.arange(s)[None, :]  # [b, s]
    valid = decode_valid_mask(qpos.reshape(-1), logical, cfg).reshape(
        b, s, logical
    )[:, None, None, :, :]
    ok = active[:, None] & (qpos < limit[:, None])
    safe_rows = jnp.minimum(qpos, logical - 1) // bs
    blk = jnp.take_along_axis(tables, safe_rows, axis=1)  # [b, s]
    blk = jnp.where(ok, blk, trash)
    off = qpos % bs
    x = params["embed"].astype(dt)[tokens]

    def gathered(c):
        # Per-layer leaf [nb, bs, ...] (the layer axis is scanned off):
        # gather table rows then flatten blocks into the logical axis.
        return c[tables].reshape(b, logical, *c.shape[2:])

    pool_keys, write_read = _kv_write_read(
        quant, lambda c, v: c.at[blk, off].set(v), gathered, dt
    )

    def layer(x, inputs):
        lp = inputs[0]
        cs = inputs[1:]
        cell = {}

        def attn_fn(q, k, v):
            new, keys_r, vals_r = write_read(cs, k, v)
            cell["kv"] = new
            return _cached_gqa_attention(q, keys_r, vals_r, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    x, new_leaves = lax.scan(
        layer, x, (params["layers"],) + tuple(pool[k] for k in pool_keys)
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, dict(zip(pool_keys, new_leaves))


@partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "steps", "gamma", "eos_id",
                     "with_sampling"),
    donate_argnames=("pool", "dcache"),
)
def _spec_decode_burst_paged(params, dparams, pool, tables, limit, dcache,
                             pos, last_tok, remaining, active, temp, keys,
                             cfg: LlamaConfig, dcfg: LlamaConfig,
                             steps: int, gamma: int, eos_id,
                             with_sampling: bool = False):
    """Paged speculative burst: same shared loop, verify against the
    block pool (tables and per-slot limits are constant for a burst —
    reservation admission pre-allocates every block a request can
    touch)."""

    def verify_fn(pool, chunk, pos, active):
        return _perslot_decode_chunk_paged(
            params, chunk, pool, tables, pos, active, limit, cfg
        )

    return _spec_burst_scan(verify_fn, dparams, pool, dcache, pos,
                            last_tok, remaining, active, temp, keys, dcfg,
                            steps, gamma, eos_id, with_sampling)


class _SpeculativeMixin:
    """Draft-model state, validation, admission mirroring, and two-sided
    prefix caching shared by the dense and paged speculative engines.
    The draft cache is always the dense slot bank (the draft is small);
    only the TARGET's storage differs between the concrete classes."""

    def __init__(self, params, cfg: LlamaConfig, *, draft_params,
                 draft_cfg: LlamaConfig, gamma: int = 4, **kwargs):
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        if gamma < 1:
            raise ValueError(
                "gamma must be >= 1 (0 proposals leaves nothing to "
                "verify; use ServingEngine for plain decoding)"
            )
        if kwargs.get("adapters"):
            raise ValueError(
                "adapters are not supported by the speculative engine "
                "(v1); use ServingEngine"
            )
        self.draft_params = draft_params
        self.dcfg = draft_cfg
        self.gamma = int(gamma)
        super().__init__(params, cfg, **kwargs)
        self.dcache = init_cache(self.dcfg, self.n_slots, self.max_len)

    def submit(self, prompt, max_new_tokens: int, prefix_id=None, **kw):
        if kw.get("top_p", 1.0) < 1.0:
            raise ValueError(
                "top_p is not supported by the speculative engine (v1): "
                "nucleus truncation must be applied consistently to both "
                "the draft and target distributions; use ServingEngine"
            )
        for unsupported in ("logprobs", "presence_penalty",
                            "frequency_penalty", "adapter"):
            if kw.get(unsupported):
                raise ValueError(
                    f"{unsupported} is not supported by the speculative "
                    "engine (v1); use ServingEngine"
                )
        return super().submit(prompt, max_new_tokens, prefix_id, **kw)

    def register_prefix(self, tokens, adapter: str | None = None) -> int:
        """Prefix caching for BOTH models: the base registration stores
        the target's prefix K/V; this adds the draft's, prefilled once —
        sharing requests skip the prefix forward on both sides. Long
        prefixes chunk on the draft side too (same O(chunk x plen)
        attention-memory bound the base class applies to the target)."""
        pid = super().register_prefix(tokens, adapter)
        toks = np.asarray(tokens, np.int32).reshape(-1)
        plen = int(toks.size)
        if self.prefill_chunk is not None and plen > self.prefill_chunk:
            c = self.prefill_chunk
            pad = -(-plen // c) * c
            padded = np.zeros((1, pad), np.int32)
            padded[0, :plen] = toks
            _, scratch = _chunked_scratch_prefill(
                self.draft_params, jnp.asarray(padded), jnp.int32(plen),
                self.dcfg, c,
            )
            scratch = {
                "k": scratch["k"][:, :, :plen],
                "v": scratch["v"][:, :, :plen],
            }
        else:
            scratch = init_cache(self.dcfg, 1, plen)
            _, scratch = _prefix_prefill(
                self.draft_params, jnp.asarray(toks[None, :]), scratch,
                self.dcfg,
            )
        self._prefixes[pid]["dk"] = scratch["k"]
        self._prefixes[pid]["dv"] = scratch["v"]
        return pid

    def _install(self, req: Request, i: int):
        placed = super()._install(req, i)
        if placed is None:
            # Paged backend out of blocks: the caller requeues; nothing
            # was placed, so nothing to mirror.
            return None
        # Mirror the admission into the DRAFT cache: same bucket, same
        # slot row; the draft's admission logits are discarded (the
        # target picked the first token).
        n = req.prompt.size
        if req.prefix_id is not None:
            pf = self._prefixes[req.prefix_id]
            if n == 0:
                self.dcache = _admit_prefix_only(
                    self.dcache, pf["dk"], pf["dv"], jnp.int32(i)
                )
            else:
                bl = self._suffix_bucket(pf["len"], n)
                padded = self._padded_prompt(req.prompt, bl)
                self.dcache, _ = _admit_prefixed(
                    self.draft_params, self.dcache, pf["dk"], pf["dv"],
                    jnp.asarray(padded), jnp.int32(i), jnp.int32(n),
                    self.dcfg,
                )
            return placed
        bl = self._bucket_len(n)
        if (self.prefill_chunk is not None and bl > self.prefill_chunk
                and bl % self.prefill_chunk == 0):
            # Long prompts chunk on the draft side too (the base class
            # already chunked the target's admission above).
            padded = self._padded_prompt(req.prompt, bl)
            _, dscratch = _chunked_scratch_prefill(
                self.draft_params, jnp.asarray(padded), jnp.int32(n),
                self.dcfg, self.prefill_chunk,
            )
            self.dcache = _install_row(
                self.dcache, dscratch, jnp.int32(i)
            )
        else:
            padded = self._padded_prompt(req.prompt, bl)
            self.dcache, _ = _admit(
                self.draft_params, self.dcache, jnp.asarray(padded),
                jnp.int32(i), jnp.int32(n), self.dcfg,
            )
        return placed

    def _with_sampling(self) -> bool:
        return any(
            r is not None and r.temperature > 0 for r in self._slot_req
        )

    @staticmethod
    def _flatten_burst(toks, emitted):
        """[steps, b, γ+1] → [steps*(γ+1), b], pass-major then
        within-pass: exactly each slot's emission order, so the base
        step() consumes it unchanged."""
        s, b, g1 = toks.shape
        toks = jnp.transpose(toks, (0, 2, 1)).reshape(s * g1, b)
        emitted = jnp.transpose(emitted, (0, 2, 1)).reshape(s * g1, b)
        return toks, emitted


class SpeculativeServingEngine(_SpeculativeMixin, ServingEngine):
    """Continuous batching with per-slot speculative decoding over the
    dense slot-bank cache.

    >>> eng = SpeculativeServingEngine(params, cfg, draft_params=dp,
    ...                                draft_cfg=dcfg, gamma=4, n_slots=4)
    >>> rid = eng.submit([1, 5, 9], max_new_tokens=64)
    >>> eng.run()   # token-exact vs ServingEngine on the same traffic

    Each scheduler sync runs `steps_per_sync` draft/verify passes, so a
    slot can emit up to steps_per_sync*(γ+1) tokens per sync (streaming
    chunks grow accordingly). Greedy requests are token-exact vs the
    plain engine; temperature>0 requests are distribution-exact vs the
    target (accept/resample) — see module doc for scope."""

    def _run_burst(self, with_logprobs: bool = False,
                   with_top_p: bool = False, with_penalties: bool = False):
        # submit() rejected everything that could set these flags.
        assert not (with_logprobs or with_top_p or with_penalties)
        (self.cache, self.dcache, self.pos, self.last_tok, self.remaining,
         self.active, toks, emitted) = _spec_decode_burst(
            self.params, self.draft_params, self.cache, self.dcache,
            self.pos, self.last_tok, self.remaining, self.active,
            self.temp, self.keys,
            self.cfg, self.dcfg, self.steps_per_sync, self.gamma,
            self.eos_id, self._with_sampling(),
        )
        toks, emitted = self._flatten_burst(toks, emitted)
        return toks, emitted, None


class PagedSpeculativeServingEngine(_SpeculativeMixin, PagedServingEngine):
    """Per-slot speculative decoding over the paged block pool: the full
    composition — continuous batching, block-table KV memory (with
    block-level prefix sharing and optional int8 pool), and draft/verify
    speculation — in one engine. Semantics match
    SpeculativeServingEngine exactly (same shared burst loop); only the
    TARGET's storage differs.

    The one paged-specific concern is the chunk's rejected-proposal tail:
    writes up to γ positions past a slot's reservation divert to the
    trash block via the per-slot `limit` vector (see
    _perslot_decode_chunk_paged) instead of corrupting a neighbor's
    blocks."""

    def _init_device_state(self):
        super()._init_device_state()
        # Reserved token extent per slot, set at admission: the paged
        # verify chunk's write guard.
        self._slot_limit = jnp.zeros((self.n_slots,), jnp.int32)

    def _install(self, req: Request, i: int):
        placed = super()._install(req, i)
        if placed is None:
            return None
        shared = 0
        if req.prefix_id is not None:
            shared = len(
                self._prefixes[req.prefix_id].get("pool_blocks", ())
            )
        self._slot_limit = self._slot_limit.at[i].set(
            (shared + len(self._slot_blocks[i])) * self.block_size
        )
        return placed

    def _run_burst(self, with_logprobs: bool = False,
                   with_top_p: bool = False, with_penalties: bool = False):
        assert not (with_logprobs or with_top_p or with_penalties)
        (self.pool, self.dcache, self.pos, self.last_tok, self.remaining,
         self.active, toks, emitted) = _spec_decode_burst_paged(
            self.params, self.draft_params, self.pool, self.tables,
            self._slot_limit, self.dcache,
            self.pos, self.last_tok, self.remaining, self.active,
            self.temp, self.keys,
            self.cfg, self.dcfg, self.steps_per_sync, self.gamma,
            self.eos_id, self._with_sampling(),
        )
        toks, emitted = self._flatten_burst(toks, emitted)
        return toks, emitted, None
