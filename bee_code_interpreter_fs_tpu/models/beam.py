"""Beam-search decoding — whole-search-fused, static shapes throughout.

Rounds out the generation suite (greedy / sampled / speculative /
continuous-batching) with the classic highest-probability-sequence
decoder. The reference project has no generation stack at all (its sandbox
runs user scripts); this sits beside the other TPU-native decoders in
`models/llama.py`.

TPU-first shape of the algorithm:

- The entire search — prefill, every step's top-k over the joint
  (beam × vocab) candidates, beam reordering, EOS freezing — is ONE jitted
  program (`lax.scan` over steps), so a networked accelerator pays one
  dispatch for the whole search instead of one per token.
- Beams live as an extra factor folded into the batch dim ([b·k] rows):
  every model call is a single large batched matmul, and "reordering
  beams" is a gather over the cache's batch axis — no dynamic shapes, no
  per-beam Python.
- Finished beams are FROZEN in-device: once a beam emits `eos_id`, its
  only continuation is `eos` at log-prob 0, so its score is immutable and
  it competes unchanged in every later top-k (the fixed-shape equivalent
  of moving it to a "finished" set).
- Length normalization (`length_penalty` α, GNMT-style
  score / ((5+len)/6)^α) is applied once at the end over each batch row's
  k candidates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    decode_step,
    init_cache,
    prefill,
    resolve_cache_len,
)

__all__ = ["beam_generate"]

_NEG_INF = -1e30


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "beam_size",
                                   "max_len"))
def beam_generate(params, prompt_tokens, cfg: LlamaConfig, *,
                  max_new_tokens: int, beam_size: int,
                  length_penalty: float = 1.0, eos_id=None,
                  max_len: int | None = None):
    """Highest-scoring continuation per prompt under beam search.

    prompt_tokens: [b, prompt_len] int32. Returns [b, prompt_len +
    max_new_tokens] int32 — the best beam per row after length
    normalization; rows that finished early are padded with `eos_id` (or
    the last argmax token when eos is off, mirroring greedy_generate's
    pinning).

    `beam_size=1` degenerates to greedy search and matches
    `greedy_generate` token-for-token; `beam_size >= vocab**steps` is
    exhaustive argmax over all continuations (tested both ways).
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    b, prompt_len = prompt_tokens.shape
    k = beam_size
    vocab = cfg.vocab_size
    max_len = resolve_cache_len(prompt_len + max_new_tokens, max_len)

    # Prefill once per PROMPT, then tile the cache across beams: [b] rows
    # become [b*k] (beam-major within each row: row i's beams occupy
    # i*k..i*k+k-1).
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt_tokens, cache, cfg)
    cache = jax.tree.map(lambda c: jnp.repeat(c, k, axis=1), cache)

    logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)  # [b, V]
    # Step 0 seeds the beams straight from the prompt's top-k tokens (the
    # joint top-k over k identical copies would just pick duplicates).
    # k > vocab (exhaustive small-vocab searches) pads dead beams at -inf;
    # they revive naturally once live beams fan out past them.
    if k <= vocab:
        scores, first_tok = lax.top_k(logp0, k)      # [b, k]
    else:
        top_scores, top_tok = lax.top_k(logp0, vocab)
        scores = jnp.full((b, k), _NEG_INF).at[:, :vocab].set(top_scores)
        first_tok = jnp.zeros((b, k), jnp.int32).at[:, :vocab].set(
            top_tok.astype(jnp.int32)
        )
    flat_tok = first_tok.reshape(b * k)
    done = (
        (flat_tok == eos_id) if eos_id is not None
        else jnp.zeros((b * k,), bool)
    )
    # Generated length per beam (tokens up to and including eos).
    gen_len = jnp.ones((b * k,), jnp.int32)
    # Token history is CARRIED (and gathered on every reorder), not emitted
    # as scan outputs: a beam's row at step t is not its ancestor's row at
    # step t+1, so per-step emissions would interleave unrelated lineages.
    seqs = jnp.zeros((b * k, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, 0].set(flat_tok)

    def body(carry, i):
        cache, scores, tok, done, gen_len, seqs = carry
        logits, cache = decode_step(
            params, tok[:, None], cache, prompt_len + i, cfg
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        if eos_id is not None:
            # A finished beam's only continuation is eos at log-prob 0:
            # its score freezes and it stays comparable in the joint top-k.
            frozen = jnp.full((vocab,), _NEG_INF).at[eos_id].set(0.0)
            logp = jnp.where(done[:, None], frozen[None, :], logp)
        cand = scores.reshape(b * k)[:, None] + logp       # [b*k, V]
        cand = cand.reshape(b, k * vocab)
        scores, flat_idx = lax.top_k(cand, k)              # [b, k]
        beam_idx = flat_idx // vocab                       # [b, k] in 0..k-1
        tok = (flat_idx % vocab).reshape(b * k).astype(jnp.int32)
        # Reorder beam state (cache rows, done flags, lengths) to follow
        # the surviving beams: gather over the folded [b*k] axis.
        src = (jnp.arange(b)[:, None] * k + beam_idx).reshape(b * k)
        cache = jax.tree.map(lambda c: jnp.take(c, src, axis=1), cache)
        done = jnp.take(done, src)
        gen_len = jnp.take(gen_len, src)
        seqs = jnp.take(seqs, src, axis=0).at[:, i + 1].set(tok)
        gen_len = gen_len + (~done).astype(jnp.int32)
        if eos_id is not None:
            done = done | (tok == eos_id)
        return (cache, scores, tok, done, gen_len, seqs), None

    steps = max_new_tokens - 1
    if steps > 0:
        (cache, scores, flat_tok, done, gen_len, seqs), _ = lax.scan(
            body,
            (cache, scores, flat_tok, done, gen_len, seqs),
            jnp.arange(steps),
        )
    tokens = seqs.reshape(b, k, max_new_tokens)

    # GNMT length normalization over each row's k finished/live beams.
    lp = ((5.0 + gen_len.reshape(b, k).astype(jnp.float32)) / 6.0) ** length_penalty
    best = jnp.argmax(scores / lp, axis=1)                 # [b]
    best_tokens = jnp.take_along_axis(
        tokens, best[:, None, None], axis=1
    )[:, 0]                                                # [b, max_new_tokens]
    return jnp.concatenate([prompt_tokens, best_tokens], axis=1)
