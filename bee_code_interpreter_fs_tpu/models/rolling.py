"""Rolling (ring-buffer) KV cache: O(window) decode memory for
sliding-window models.

cfg.sliding_window bounds ATTENTION to the last W keys, and the flash
kernel already bounds prefill COMPUTE to O(t·W) — but the standard cache
(init_cache) still holds max_len positions. For streaming/serving beyond
the window that's the wrong residency: a windowed model only ever reads
the last W keys (plus the attention sinks), so the cache can be a ring of
W slots + a write-once sink buffer, and decode memory becomes O(W+S) per
layer regardless of how long the stream runs.

Mechanics (softmax is permutation-invariant over keys, so ring ORDER never
matters — only the visible SET does):
- slot ``pos % W`` is overwritten each step; the position a slot currently
  holds is ``p_j = pos - ((pos - j) % W)``, which is negative (never
  written) early on and always in ``(pos-W, pos]`` once warm;
- ring validity: ``p_j >= max(S, 0)`` — sink positions live in their own
  buffer (write-once, valid when ``s <= pos``), so the early-phase ring
  copies of them are masked out rather than double-counted;
- keys are stored post-RoPE at absolute positions, exactly like the
  standard cache, so scores agree with the full-cache path bit-for-bit
  up to contraction order.

`rolling_decode_logits` (teacher-forced, the equivalence oracle) and
`rolling_greedy_generate` (fused greedy loop) both scan step-by-step from
position 0 — prefill IS the stream here; batch prefill belongs to the
bounded-length path (prefill/decode_chunk)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _cached_gqa_attention,
    _rms_norm,
    _w,
    transformer_block,
)


def init_rolling_cache(cfg: LlamaConfig, batch_size: int):
    """Ring of cfg.sliding_window K/V slots + cfg.attention_sinks
    write-once slots per layer. Sizes come from cfg ONLY: the decode step
    derives its visible-key semantics from the cache shapes, so an
    override here would silently diverge from forward() under the same
    config."""
    W = cfg.sliding_window
    S = cfg.attention_sinks
    if W <= 0:
        raise ValueError("rolling cache needs a sliding window (W > 0)")
    dt = jnp.dtype(cfg.dtype)
    L, nkv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    shape = lambda n: (L, batch_size, n, nkv, hd)  # noqa: E731
    cache = {
        "k": jnp.zeros(shape(W), dt),
        "v": jnp.zeros(shape(W), dt),
    }
    if S > 0:
        cache["sink_k"] = jnp.zeros(shape(S), dt)
        cache["sink_v"] = jnp.zeros(shape(S), dt)
    return cache


def rolling_decode_step(params, tokens, cache, pos, cfg: LlamaConfig):
    """One decode step against the ring: tokens [b, 1] at position `pos`
    (traced). Returns (logits [b, vocab] float32, updated cache)."""
    if cfg.sliding_window <= 0:
        raise ValueError("rolling_decode_step requires cfg.sliding_window > 0")
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    W = cache["k"].shape[2]
    S = cache["sink_k"].shape[2] if "sink_k" in cache else 0

    # Ring slot j currently holds absolute position pos - ((pos - j) % W)
    # (negative = never written). Valid ring keys: written, and not a sink
    # position (those attend from the sink buffer to avoid double counting).
    j = jnp.arange(W)
    p_j = pos - ((pos - j) % W)
    ring_valid = p_j >= S
    if S > 0:
        sink_valid = jnp.arange(S) <= pos
        valid = jnp.concatenate([sink_valid, ring_valid])[None, :]
    else:
        valid = ring_valid[None, :]
    valid = valid[None, None, None]  # -> broadcast over [b, g, r, t, k]

    slot = pos % W
    x = params["embed"].astype(dt)[tokens]

    def layer(x, inputs):
        if S > 0:
            lp, ck, cv, sk, sv = inputs
        else:
            lp, ck, cv = inputs
        cell = {}

        def attn_fn(q, k, v):
            new_k = lax.dynamic_update_slice(ck, k, (0, slot, 0, 0))
            new_v = lax.dynamic_update_slice(cv, v, (0, slot, 0, 0))
            if S > 0:
                # Write-once: only positions < S land in the sink buffer.
                sink_slot = jnp.minimum(pos, S - 1)
                write = (pos < S).astype(k.dtype)
                old_k = lax.dynamic_slice(
                    sk, (0, sink_slot, 0, 0), k.shape
                )
                old_v = lax.dynamic_slice(
                    sv, (0, sink_slot, 0, 0), v.shape
                )
                new_sk = lax.dynamic_update_slice(
                    sk, write * k + (1 - write) * old_k, (0, sink_slot, 0, 0)
                )
                new_sv = lax.dynamic_update_slice(
                    sv, write * v + (1 - write) * old_v, (0, sink_slot, 0, 0)
                )
                cell["kv"] = (new_k, new_v, new_sk, new_sv)
                keys = jnp.concatenate([new_sk, new_k], axis=1)
                values = jnp.concatenate([new_sv, new_v], axis=1)
            else:
                cell["kv"] = (new_k, new_v)
                keys, values = new_k, new_v
            return _cached_gqa_attention(q, keys, values, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    if S > 0:
        xs = (params["layers"], cache["k"], cache["v"],
              cache["sink_k"], cache["sink_v"])
    else:
        xs = (params["layers"], cache["k"], cache["v"])
    x, new = lax.scan(layer, x, xs)
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ _w(params["lm_head"], dt)).astype(jnp.float32)
    out = {"k": new[0], "v": new[1]}
    if S > 0:
        out["sink_k"], out["sink_v"] = new[2], new[3]
    return logits, out


@partial(jax.jit, static_argnames=("cfg",))
def rolling_decode_logits(params, tokens, cfg: LlamaConfig):
    """Teacher-forced logits [b, t, vocab] via the ring — the equivalence
    oracle against forward() with the same window/sinks, at O(W+S) cache
    residency instead of O(t)."""
    b, t = tokens.shape
    cache = init_rolling_cache(cfg, b)

    def step(carry, inputs):
        cache = carry
        pos, tok = inputs
        logits, cache = rolling_decode_step(
            params, tok[:, None], cache, pos, cfg
        )
        return cache, logits

    _, logits = lax.scan(
        step, cache, (jnp.arange(t), tokens.T)
    )
    return logits.transpose(1, 0, 2)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens"))
def rolling_greedy_generate(params, prompt_tokens, cfg: LlamaConfig, *,
                            max_new_tokens: int):
    """Fused greedy decode over the ring: unbounded-stream serving shape —
    cache bytes depend on (window + sinks), never on total length."""
    b, p = prompt_tokens.shape
    cache = init_rolling_cache(cfg, b)
    total = p + max_new_tokens

    def step(carry, pos):
        cache, last_logits, buf = carry
        prompt_tok = lax.dynamic_slice(
            buf, (0, jnp.minimum(pos, p - 1)), (b, 1)
        )[:, 0]
        gen_tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        tok = jnp.where(pos < p, prompt_tok, gen_tok)
        logits, cache = rolling_decode_step(
            params, tok[:, None], cache, pos, cfg
        )
        return (cache, logits, buf), tok

    _, toks = lax.scan(
        step,
        (cache, jnp.zeros((b, cfg.vocab_size), jnp.float32), prompt_tokens),
        jnp.arange(total),
    )
    # toks[pos] is the token FED at position pos: the prompt for pos < p,
    # then each argmax of the previous step's logits — i.e. exactly the
    # [b, prompt + max_new_tokens] sequence greedy_generate returns.
    return toks.T
