"""Llama-class decoder-only transformer, TPU-first.

This is the framework's flagship compute payload: BASELINE config 5 runs
Llama-class inference *through Execute*, and `__graft_entry__.py` jits this
model's forward/train step for the driver's single-chip and multi-chip
checks. Design choices are TPU-native, not a port of any torch code:

- Parameters are a flat pytree of jnp arrays; the whole model is pure
  functions — jit/grad/shard_map compose directly.
- bfloat16 activations/weights on the matmul path (MXU-native), float32 for
  RMSNorm statistics, softmax accumulation, and the final logits/loss.
- Distribution is declarative: `param_specs()` returns a PartitionSpec pytree
  (tensor parallel over the "tp" mesh axis: attention heads and MLP hidden
  sharded; XLA inserts the per-block collectives). Batch rides "dp",
  sequence rides "sp" via ring attention (parallel/ring_attention.py) wrapped
  in shard_map — exact causal attention over sequence shards.
- Layers are stacked (scan-style weight layout [n_layers, ...]) and iterated
  with `lax.scan` so compile time stays flat in depth.

No reference-code lineage: the reference (MikeDepies/bee-code-interpreter-fs)
contains no model code at all.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from bee_code_interpreter_fs_tpu.parallel.mesh import shard_map

from bee_code_interpreter_fs_tpu.parallel.ring_attention import ring_attention

NEG_INF_LOGIT = -1e30  # finite mask value for truncated-sampling logits


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # Mixture-of-experts (Mixtral-class): 0 = dense MLP. With n_experts > 0
    # every layer's MLP becomes n_experts expert MLPs with top-k routing;
    # experts shard over the "ep" mesh axis (param_specs).
    n_experts: int = 0
    n_experts_per_token: int = 2
    # Single-shard attention implementation: "plain" (XLA fused dense) or
    # "flash" (the Pallas kernel, ops/flash_attention.py — O(t·d) HBM
    # instead of O(t²), the long-context choice). Ring attention (mesh with
    # sp > 1) takes precedence over either.
    attn_impl: str = "plain"
    # Rematerialize decoder blocks on the backward pass (jax.checkpoint
    # around the layer-scan body, dot-saveable policy): activation memory
    # for training drops from O(n_layers·b·t·dim) to ~one block, for one
    # extra forward's FLOPs — how long-context training fits HBM.
    remat: bool = False
    # MoE dispatch implementation: "dense" computes every expert over every
    # token (zero dynamic shapes, ep-shardable via param specs — the right
    # trade at small scale) while "capacity" routes each token to only its
    # top-k experts through a fixed per-expert capacity buffer
    # (scatter/gather, FLOPs drop ~E/(k·factor)-fold; tokens overflowing an
    # expert's buffer lose that expert's contribution, the standard
    # GShard/Switch trade). Single-shard path; meshes keep dense dispatch.
    moe_impl: str = "dense"
    moe_capacity_factor: float = 1.25
    # Sequence-parallel strategy when the mesh's "sp" axis is > 1:
    # "ring" streams K/V chunks around the ring (bandwidth-optimal,
    # parallel/ring_attention.py) while "ulysses" repartitions via two
    # all-to-alls and runs full-sequence attention on a head subset per
    # device (latency-friendly; heads are also tp-sharded, so it needs
    # (n_heads / tp) % sp == 0 — parallel/ulysses.py).
    sp_impl: str = "ring"
    # Sliding-window attention (Mistral-style): each position attends to
    # at most the last `sliding_window` keys (itself included). 0 = full
    # causal. Applies to prefill (plain and flash paths — the flash kernel
    # skips out-of-window tiles' DMAs AND FLOPs, so prefill scales
    # O(t·window)) and to the KV-cache decode path. Not composed with
    # sequence parallelism (sp > 1 raises).
    sliding_window: int = 0
    # StreamingLLM attention sinks: with a sliding window, keep the first
    # `attention_sinks` positions visible to EVERY query — the trick that
    # keeps windowed models stable far past their window. 0 = none;
    # ignored without a window.
    attention_sinks: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Small config for tests / driver dry-runs (shapes divisible by an
        8-way mesh: heads % tp, batch % dp, seq % sp)."""
        base = dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
            hidden_dim=128, max_seq_len=128,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()  # defaults are the 7B shape

    @staticmethod
    def llama2_13b() -> "LlamaConfig":
        """Llama-2-13B geometry: 40L / 5120 / 13824, MHA."""
        return LlamaConfig(
            vocab_size=32000, dim=5120, n_layers=40, n_heads=40,
            n_kv_heads=40, hidden_dim=13824, max_seq_len=4096,
        )

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        """Llama-3-8B geometry: GQA 32q/8kv, 128k vocab, theta 5e5."""
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, max_seq_len=8192,
            rope_theta=500000.0,
        )

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        """Mixtral-8x7B geometry: 8 experts, top-2 routing, GQA 32q/8kv."""
        return LlamaConfig(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, max_seq_len=32768,
            rope_theta=1000000.0, n_experts=8, n_experts_per_token=2,
        )


# ---------------------------------------------------------------- params

def init_params(key, cfg: LlamaConfig):
    """Stacked-layer parameter pytree ([n_layers, ...] leading axis)."""
    dt = jnp.dtype(cfg.dtype)
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    k_emb, k_attn, k_mlp, k_out = jax.random.split(key, 4)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * fan_in ** -0.5).astype(dt)

    L = cfg.n_layers
    ka = jax.random.split(k_attn, 4)
    km = jax.random.split(k_mlp, 4)
    if cfg.n_experts > 0:
        E = cfg.n_experts
        mlp = {
            "router": dense(km[3], (L, cfg.dim, E), cfg.dim),
            "w_gate": dense(km[0], (L, E, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_up": dense(km[1], (L, E, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_down": dense(
                km[2], (L, E, cfg.hidden_dim, cfg.dim), cfg.hidden_dim
            ),
        }
    else:
        mlp = {
            "w_gate": dense(km[0], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_up": dense(km[1], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_down": dense(km[2], (L, cfg.hidden_dim, cfg.dim), cfg.hidden_dim),
        }
    return {
        "embed": dense(k_emb, (cfg.vocab_size, cfg.dim), 1.0),
        "layers": {
            "attn_norm": jnp.ones((L, cfg.dim), jnp.float32),
            "wq": dense(ka[0], (L, cfg.dim, nh * hd), cfg.dim),
            "wk": dense(ka[1], (L, cfg.dim, nkv * hd), cfg.dim),
            "wv": dense(ka[2], (L, cfg.dim, nkv * hd), cfg.dim),
            "wo": dense(ka[3], (L, nh * hd, cfg.dim), nh * hd),
            "mlp_norm": jnp.ones((L, cfg.dim), jnp.float32),
            **mlp,
        },
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "lm_head": dense(k_out, (cfg.dim, cfg.vocab_size), cfg.dim),
    }


def param_specs(cfg: LlamaConfig):
    """PartitionSpec pytree mirroring init_params: tensor parallel on "tp".

    Projections shard their head/hidden dimension; wo/w_down shard the
    contracting dimension so each block needs exactly one psum (XLA inserts
    it). Embedding shards the vocab dim; norms replicate. MoE experts shard
    their expert dimension over "ep" AND their hidden dimension over "tp" —
    the weighted combine over experts becomes the per-layer ep psum.
    """
    if cfg.n_experts > 0:
        mlp = {
            "router": P(None, None, None),
            "w_gate": P(None, "ep", None, "tp"),
            "w_up": P(None, "ep", None, "tp"),
            "w_down": P(None, "ep", "tp", None),
        }
    else:
        mlp = {
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        }
    return {
        "embed": P("tp", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            **mlp,
        },
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


# ---------------------------------------------------------------- forward

def _rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, theta, offset=0):
    """Rotary embedding over [b, t, h, d]; `offset` shifts the position
    index (incremental decoding: the single new token sits at `pos`).
    `offset` may be a scalar (whole batch at one position) or a [b] vector
    (continuous batching: every slot decodes at its own sequence length —
    models/serving.py)."""
    b, t, h, d = x.shape
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    off = jnp.asarray(offset, dtype=jnp.float32)
    # [b, t] positions; a scalar offset broadcasts to identical rows.
    positions = jnp.arange(t, dtype=jnp.float32)[None, :] + jnp.atleast_1d(off)[:, None]
    angles = positions[..., None] * freqs[None, None, :]  # [b|1, t, d/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(b, t, h, d)


def _route(h, lp, cfg: LlamaConfig):
    """Top-k expert routing (softmax over router logits, renormalized over
    the selected k) — the ONE routing rule both MoE dispatch
    implementations share; works over any leading dims."""
    router_logits = (h @ lp["router"].astype(h.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_w, top_i = lax.top_k(probs, cfg.n_experts_per_token)
    return top_w / top_w.sum(axis=-1, keepdims=True), top_i


def _moe_mlp(h, lp, cfg: LlamaConfig):
    """Mixtral-class top-k MoE MLP, SPMD-first dense dispatch.

    Router picks k of E experts per token (softmax over the top-k logits
    renormalized); the expert computation is written as einsums over a
    stacked [E, dim, hidden] weight tensor, so GSPMD partitions the E
    dimension across the "ep" mesh axis from the param shardings alone —
    each device runs its local experts over the full token set and the
    weighted combine over E lowers to one psum on ep per layer. Dense
    dispatch trades FLOPs (every expert sees every token, inflation E/k)
    for zero dynamic shapes and no all-to-all — the right trade below the
    scale where ragged dispatch kernels pay for themselves; swap in a
    Pallas ragged dispatch at Mixtral-8x7B scale.
    """
    top_w, top_i = _route(h, lp, cfg)  # [b, t, k]
    # Dense per-token expert weights: zero outside the top-k.
    weights = (
        jax.nn.one_hot(top_i, cfg.n_experts, dtype=jnp.float32)
        * top_w[..., None]
    ).sum(axis=-2)  # [b, t, E]
    gate = jax.nn.silu(jnp.einsum("btd,edh->bteh", h, _w(lp["w_gate"], h.dtype)))
    up = jnp.einsum("btd,edh->bteh", h, _w(lp["w_up"], h.dtype))
    y = jnp.einsum("bteh,ehd->bted", gate * up, _w(lp["w_down"], h.dtype))
    return jnp.einsum("bted,bte->btd", y, weights.astype(y.dtype))


def _moe_mlp_capacity(h, lp, cfg: LlamaConfig):
    """Capacity-based top-k MoE dispatch (GShard/Switch style), the
    FLOP-efficient alternative to `_moe_mlp`'s dense dispatch: each token
    reaches only its k routed experts through fixed [E, capacity] buffers
    — expert compute drops from E token-passes to ~factor·k — with
    linear-cost scatter/gather (no quadratic one-hot dispatch matmuls).

    capacity = ceil(factor · k · T / E) is static (shapes only). A token
    slot that overflows its expert's buffer is DROPPED for that expert
    (its routing weight contributes nothing; the residual stream still
    carries the token) — the standard trade; factor >= E/k makes drops
    impossible and the result equals dense dispatch exactly (tested).
    Single-shard implementation: mesh runs keep the ep-shardable dense
    path."""
    b, t, d = h.shape
    E, k = cfg.n_experts, cfg.n_experts_per_token
    T = b * t
    x = h.reshape(T, d)
    top_w, top_i = _route(x, lp, cfg)                       # [T, k]

    import math

    cap = max(1, math.ceil(cfg.moe_capacity_factor * k * T / E))
    flat_e = top_i.reshape(T * k)                           # expert per slot
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)     # [T*k, E]
    # Position of each slot within its expert's buffer: count of earlier
    # slots routed to the same expert.
    pos = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)
    keep = pos < cap
    # Overflowing slots scatter into a trash row past the buffers.
    slot_idx = jnp.where(keep, flat_e * cap + pos, E * cap)
    xk = jnp.repeat(x, k, axis=0)                           # [T*k, d]
    xe = jnp.zeros((E * cap + 1, d), h.dtype).at[slot_idx].add(xk)
    xe = xe[:-1].reshape(E, cap, d)

    gate = jax.nn.silu(
        jnp.einsum("ecd,edh->ech", xe, _w(lp["w_gate"], h.dtype))
    )
    up = jnp.einsum("ecd,edh->ech", xe, _w(lp["w_up"], h.dtype))
    ye = jnp.einsum("ech,ehd->ecd", gate * up, _w(lp["w_down"], h.dtype))

    yk = ye.reshape(E * cap, d)[jnp.where(keep, slot_idx, 0)]
    w_slot = (top_w.reshape(T * k) * keep).astype(yk.dtype)
    y = (yk * w_slot[:, None]).reshape(T, k, d).sum(axis=1)
    return y.reshape(b, t, d)


def _plain_causal_attention(q, k, v, scale, window: int = 0, sinks: int = 0):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    if window > 0:
        # Sliding window: drop keys older than q_pos - window + 1 — except
        # the first `sinks` keys (StreamingLLM attention sinks), which
        # every query keeps seeing.
        visible = jnp.tril(jnp.ones((t, t), bool), -window) == 0
        if sinks > 0:
            visible |= (jnp.arange(t) < sinks)[None, :]
        mask &= visible
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _expand_gqa(k, v, n_heads):
    """Repeat kv heads up to n_heads (full-sequence attention paths; the
    decode path contracts against unexpanded kv instead — no cache copy)."""
    rep = n_heads // k.shape[2]
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _w(leaf, dt):
    """Matmul-weight accessor: dense arrays pass through (cast is a no-op at
    the model dtype); int8-quantized {"q","s"} and int4-packed {"q4","s4"}
    leaves (models/quant.py) dequantize HERE, at the use site inside the
    layer scan — XLA then reads 1 (or 0.5) byte/param from HBM and fuses
    unpack/convert/scale into the matmul operand path, which is the whole
    point of weight-only quantization on a decode path that is
    weight-bandwidth-bound.

    LoRA composite leaves {"base","lora_a","lora_b"} (models/lora.py)
    resolve recursively to base + a @ b — the base may itself be a
    quantized leaf (QLoRA), and every model path (forward, fused decode,
    serving, pipeline) picks adapters up through this one accessor."""
    from bee_code_interpreter_fs_tpu.models.quant import (
        dequantize,
        dequantize4,
        is_quantized,
        is_quantized4,
    )

    from bee_code_interpreter_fs_tpu.models.lora import is_lora_leaf

    if isinstance(leaf, dict) and "lora_a_stack" in leaf:
        raise TypeError(
            "multi-adapter LoRA leaves select weights PER BATCH ROW and "
            "have no single-matrix form; they are consumed activation-side "
            "by _mm (all model matmuls route through it)"
        )
    if is_lora_leaf(leaf):
        # Correctness fallback only: materializes the full [in, out] delta.
        # Every model matmul goes through _mm below, which applies the
        # low-rank update activation-side and never builds this product.
        return _w(leaf["base"], dt) + (
            leaf["lora_a"].astype(dt) @ leaf["lora_b"].astype(dt)
        )
    if is_quantized(leaf):
        return dequantize(leaf, dt)
    if is_quantized4(leaf):
        return dequantize4(leaf, dt)
    return leaf.astype(dt)


def _mm(h, leaf, dt):
    """``h @ W`` for any weight-leaf kind. LoRA composite leaves apply
    activation-side — ``h @ base + (h @ a) @ b`` — so the update costs two
    skinny matmuls (in×r, r×out) and the dense [in, out] delta is never
    materialized; the (possibly int8/int4-quantized — QLoRA) base keeps its
    reduced HBM traffic on the weight-bandwidth-bound decode path."""
    from bee_code_interpreter_fs_tpu.models.lora import is_lora_leaf

    if isinstance(leaf, dict) and "lora_a_stack" in leaf:
        # Multi-adapter serving (lora.multi_lora_wrap): batch row i applies
        # adapter lora_ids[i] — gather the per-row [in, r]/[r, out] pair
        # and run two batched skinny matmuls. Inside the layer scan the
        # stacks are [N, in, r]/[N, r, out] and lora_ids is [b].
        ids = leaf["lora_ids"]
        # Gather BEFORE casting: convert only the b selected adapters, not
        # the whole bank.
        a_sel = leaf["lora_a_stack"][ids].astype(dt)
        b_sel = leaf["lora_b_stack"][ids].astype(dt)
        delta = jnp.einsum("btr,bro->bto",
                           jnp.einsum("btd,bdr->btr", h, a_sel), b_sel)
        return _mm(h, leaf["base"], dt) + delta
    if is_lora_leaf(leaf):
        return _mm(h, leaf["base"], dt) + (
            h @ leaf["lora_a"].astype(dt)
        ) @ leaf["lora_b"].astype(dt)
    return h @ _w(leaf, dt)


def transformer_block(x, lp, cfg: LlamaConfig, attn_fn, *, rope_offset=0):
    """One pre-norm decoder block: attention + (dense | MoE) MLP, residual
    around each. `attn_fn(q, k, v) -> attn` receives UNexpanded kv heads
    ([b, t, n_kv_heads, hd]) so callers can swap plain causal attention,
    ring attention (sp), or a KV-cached variant without duplicating the
    block arithmetic; `rope_offset` positions incremental-decode tokens."""
    b, t, _ = x.shape
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    dt = x.dtype
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = _mm(h, lp["wq"], dt).reshape(b, t, nh, hd)
    k = _mm(h, lp["wk"], dt).reshape(b, t, nkv, hd)
    v = _mm(h, lp["wv"], dt).reshape(b, t, nkv, hd)
    q = _rope(q, cfg.rope_theta, offset=rope_offset)
    k = _rope(k, cfg.rope_theta, offset=rope_offset)
    attn = attn_fn(q, k, v)
    x = x + _mm(attn.reshape(b, t, nh * hd), lp["wo"], dt)

    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        if cfg.moe_impl not in ("dense", "capacity"):
            raise ValueError(
                f"unknown moe_impl {cfg.moe_impl!r}; use 'dense' or "
                "'capacity'"
            )
        moe = _moe_mlp_capacity if cfg.moe_impl == "capacity" else _moe_mlp
        x = x + moe(h, lp, cfg)
    else:
        gate = jax.nn.silu(_mm(h, lp["w_gate"], dt))
        x = x + _mm(gate * _mm(h, lp["w_up"], dt), lp["w_down"], dt)
    return x


def forward(params, tokens, cfg: LlamaConfig, *, mesh: Mesh | None = None):
    """Token ids [b, t] -> logits [b, t, vocab] (float32).

    If `mesh` has an "sp" axis of size > 1, attention runs sequence-parallel
    with the strategy cfg.sp_impl selects — "ring" (shard_map + ppermute
    K/V streaming) or "ulysses" (two all_to_alls, full-sequence attention
    on a head subset per device); otherwise plain fused causal attention.
    XLA's GSPMD handles dp/tp either way.
    """
    dt = jnp.dtype(cfg.dtype)
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    scale = hd ** -0.5
    use_ring = mesh is not None and mesh.shape.get("sp", 1) > 1
    if (mesh is not None and cfg.n_experts > 0
            and cfg.moe_impl == "capacity"):
        raise ValueError(
            "moe_impl='capacity' is the single-shard dispatch (its flat "
            "scatter defeats ep sharding); meshes use the ep-shardable "
            "dense dispatch — drop the mesh or set moe_impl='dense'"
        )
    if use_ring and cfg.sliding_window > 0:
        raise ValueError(
            "sliding_window is not composed with sequence parallelism "
            "(windowing across ring/ulysses shards is unimplemented); "
            "use a mesh without an sp axis"
        )
    if use_ring:
        # attn_impl="flash" composes with BOTH sp strategies: ring uses the
        # Pallas partial kernel per step (no per-chunk-pair score tensor);
        # ulysses runs the full flash kernel over the gathered sequence.
        if cfg.sp_impl == "ulysses":
            from bee_code_interpreter_fs_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            sp_fn = ulysses_attention
        elif cfg.sp_impl == "ring":
            sp_fn = ring_attention
        else:
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got {cfg.sp_impl!r}"
            )
        ring = shard_map(
            partial(
                sp_fn,
                axis_name="sp",
                scale=scale,
                use_flash=cfg.attn_impl == "flash",
                flash_interpret=jax.default_backend() != "tpu",
            ),
            mesh=mesh,
            in_specs=(P("dp", "sp", "tp", None),) * 3,
            out_specs=P("dp", "sp", "tp", None),
            check_rep=False,
        )

    x = params["embed"].astype(dt)[tokens]  # [b, t, dim]
    if use_ring:
        if cfg.sp_impl == "ulysses":
            # Ulysses takes UNexpanded kv: when the kv head count divides
            # sp it rides the all-to-alls at 1/rep the bytes and expands
            # after the repartition (parallel/ulysses.py).
            attn_fn = lambda q, k, v: ring(q, k, v)  # noqa: E731
        else:
            attn_fn = lambda q, k, v: ring(q, *_expand_gqa(k, v, nh))  # noqa: E731
    elif cfg.attn_impl == "flash":
        from bee_code_interpreter_fs_tpu.ops.flash_attention import (
            flash_attention,
        )

        # Pallas lowers via Mosaic on TPU; elsewhere (tests, CPU dev) the
        # same kernel runs interpreted.
        interpret = jax.default_backend() != "tpu"
        attn_fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, *_expand_gqa(k, v, nh), scale=scale,
            window=cfg.sliding_window, sinks=cfg.attention_sinks,
            interpret=interpret,
        )
    else:
        attn_fn = lambda q, k, v: _plain_causal_attention(  # noqa: E731
            q, *_expand_gqa(k, v, nh), scale,
            window=cfg.sliding_window, sinks=cfg.attention_sinks,
        )

    def layer(x, lp):
        return transformer_block(x, lp, cfg, attn_fn), None

    if cfg.remat:
        # Rematerialize each block on the backward pass: activation
        # residency drops from O(n_layers · b · t · dim) to one block's
        # worth (the scan carry), bought with one extra forward — the
        # standard long-context training trade on HBM-limited chips.
        # Matmul results still save (they're the expensive thing to
        # recompute); only cheap elementwise/norm work replays.
        # prevent_cse=False: safe (and documented as the right call) under
        # lax.scan, and skips optimization barriers that would block XLA
        # fusion inside every iteration.
        layer = jax.checkpoint(
            layer,
            prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    x, _ = lax.scan(layer, x, params["layers"])
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ _w(params["lm_head"], dt)).astype(jnp.float32)


# ---------------------------------------------------------------- decoding

def init_cache(cfg: LlamaConfig, batch_size: int, max_len: int | None = None):
    """Stacked per-layer KV cache (unexpanded GQA heads — memory scales with
    n_kv_heads, not n_heads): {"k"|"v": [L, b, max_len, n_kv, head_dim]}."""
    max_len = max_len or cfg.max_seq_len
    shape = (
        cfg.n_layers,
        batch_size,
        max_len,
        cfg.n_kv_heads,
        cfg.head_dim,
    )
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def resolve_cache_len(needed: int, max_len: int | None, *,
                      what: str = "prompt+new") -> int:
    """The generation-cache sizing contract, in ONE place: default max_len
    to exactly what the generation needs; reject an explicit max_len that
    can't hold it (dynamic_update_slice would silently clamp writes past
    the cache's end — wrong generations with no error)."""
    max_len = max_len or needed
    if max_len < needed:
        raise ValueError(
            f"max_len={max_len} < {what}={needed}: cache too small"
        )
    return max_len


def decode_valid_mask(q_pos, max_len, cfg: LlamaConfig):
    """Which cache positions queries at positions `q_pos` [n] may attend:
    causal prefix, minus anything a sliding window retires, plus
    StreamingLLM sinks. Returns bool [n, max_len]. The ONE home of the
    window/sinks visibility formula for every cached-decode path
    (decode_chunk, decode_step via decode_chunk, the continuous-batching
    engine's per-slot step in models/serving.py)."""
    valid = jnp.arange(max_len)[None, :] <= q_pos[:, None]
    if cfg.sliding_window > 0:
        visible = (
            jnp.arange(max_len)[None, :] > q_pos[:, None] - cfg.sliding_window
        )
        if cfg.attention_sinks > 0:
            visible |= (jnp.arange(max_len) < cfg.attention_sinks)[None, :]
        valid &= visible
    return valid


def _cached_gqa_attention(q, keys, values, valid, scale):
    """Attention of `q` [b, t, nh, hd] against an UNexpanded cache
    ([b, max, nkv, hd]) via a grouped contraction — no jnp.repeat copy of
    the whole cache on the per-token hot path (the n_kv_heads memory saving
    init_cache advertises must hold at read time too)."""
    b, t, nh, hd = q.shape
    nkv = keys.shape[2]
    rep = nh // nkv
    qg = q.reshape(b, t, nkv, rep, hd)
    s = jnp.einsum("btgrd,bkgd->bgrtk", qg, keys).astype(jnp.float32) * scale
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    attn = jnp.einsum("bgrtk,bkgd->btgrd", p, values)
    return attn.reshape(b, t, nh, hd)


def decode_step(params, tokens, cache, pos, cfg: LlamaConfig):
    """One incremental decoding step.

    tokens: [b, 1] int32 — the token at position `pos` (a traced scalar, so
    one compile serves every step). Returns (logits [b, vocab] float32,
    updated cache). Attention reads the cache up to and including `pos`
    (static cache length + a position mask — no dynamic shapes under jit).
    Jit with ``donate_argnums=(2,)`` so the cache updates in place instead
    of copying [L, b, max, nkv, hd] twice per token (generate() does).
    """
    # The s=1 case of decode_chunk (the valid mask degenerates to
    # arange(max_len) <= pos) — delegated so the cache-write and
    # masked-attention plumbing exists exactly once.
    logits, cache = decode_chunk(params, tokens, cache, pos, cfg)
    return logits[:, 0], cache


def prefill(params, tokens, cache, cfg: LlamaConfig):
    """Process the whole prompt in ONE forward pass, writing every K/V
    position into the cache (one device dispatch and one cache write per
    layer — not prompt_len sequential decode steps). Returns (last-position
    logits [b, vocab] float32, updated cache)."""
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    t = tokens.shape[1]
    if t > cache["k"].shape[2]:
        raise ValueError(
            f"prompt length {t} exceeds cache max_len {cache['k'].shape[2]}"
        )
    x = params["embed"].astype(dt)[tokens]

    def layer(x, inputs):
        lp, ck, cv = inputs
        cell = {}

        def attn_fn(q, k, v):
            cell["kv"] = (
                lax.dynamic_update_slice(ck, k, (0, 0, 0, 0)),
                lax.dynamic_update_slice(cv, v, (0, 0, 0, 0)),
            )
            return _plain_causal_attention(
                q, *_expand_gqa(k, v, cfg.n_heads), scale,
                window=cfg.sliding_window, sinks=cfg.attention_sinks,
            )

        x = transformer_block(x, lp, cfg, attn_fn)
        return x, cell["kv"]

    x, (new_k, new_v) = lax.scan(layer, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, t - 1] @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def decode_chunk(params, tokens, cache, pos, cfg: LlamaConfig):
    """Process `s` tokens at positions pos..pos+s-1 against the cache — the
    chunked middle ground between prefill() (pos=0, empty cache) and
    decode_step() (s=1): each chunk token attends to every cache position
    up to itself (cache prefix + the chunk's own causal prefix). Returns
    (logits [b, s, vocab] float32 for ALL s positions, updated cache).

    This is speculative decoding's verify pass (score γ draft tokens in one
    target forward) and doubles as chunked prefill for prompts longer than
    one pass should materialize.
    """
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    s = tokens.shape[1]
    max_len = cache["k"].shape[2]
    # Chunk-local query i (global pos+i) sees cache positions <= pos+i
    # (and, with a sliding window, none older than pos+i-window+1).
    valid = decode_valid_mask(pos + jnp.arange(s), max_len, cfg)[None, None, None]
    x = params["embed"].astype(dt)[tokens]

    def layer(x, inputs):
        lp, ck, cv = inputs
        cell = {}

        def attn_fn(q, k, v):
            new_k = lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
            new_v = lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
            cell["kv"] = (new_k, new_v)
            return _cached_gqa_attention(q, new_k, new_v, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    x, (new_k, new_v) = lax.scan(layer, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def _spec_setup(draft_params, target_params, prompt_tokens, cfg_draft,
                cfg_target, *, max_new_tokens, gamma, max_len, plain_decoder):
    """Shared speculative preamble: validation, cache sizing (slack: the
    last pass may overshoot max_new_tokens by up to γ), dual prefill, and
    the output buffer with the prompt written. Mirrors greedy/
    sample_generate on max_len: an explicit value that can't hold the
    generation is a caller error, never silently enlarged — a caller sizing
    sharded caches by max_len must get what it asked for."""
    if cfg_draft.vocab_size != cfg_target.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if gamma < 1:
        raise ValueError(
            "gamma must be >= 1 (0 proposals leaves nothing to verify; "
            f"use {plain_decoder} for plain decoding)"
        )
    b, p = prompt_tokens.shape
    total = p + max_new_tokens + gamma + 1
    max_len = resolve_cache_len(total, max_len, what="prompt+new+gamma+1")
    d_cache = init_cache(cfg_draft, b, max_len)
    t_cache = init_cache(cfg_target, b, max_len)
    t_logits, t_cache = prefill(target_params, prompt_tokens, t_cache, cfg_target)
    _, d_cache = prefill(draft_params, prompt_tokens, d_cache, cfg_draft)
    buf = jnp.zeros((b, total), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt_tokens, (0, 0))
    return b, p, total, d_cache, t_cache, t_logits, buf


@partial(
    jax.jit,
    static_argnames=("cfg_draft", "cfg_target", "max_new_tokens", "gamma", "max_len"),
)
def speculative_generate(draft_params, target_params, prompt_tokens,
                         cfg_draft: LlamaConfig, cfg_target: LlamaConfig, *,
                         max_new_tokens: int, gamma: int = 4,
                         max_len: int | None = None):
    """Greedy speculative decoding, fully jitted: a cheap DRAFT model
    proposes γ tokens autoregressively, the TARGET scores all of them in
    ONE decode_chunk forward, and the longest agreeing prefix plus the
    target's own next token are emitted — up to γ+1 tokens per target
    pass instead of 1. The output is EXACTLY greedy_generate(target): the
    draft only decides how many target tokens each pass yields, never what
    they are (greedy acceptance = token equality, so every emitted token is
    the target's argmax given its prefix).

    Batch rows advance in lockstep by the BATCH-MINIMUM acceptance (per-row
    positions would need ragged caches); rows that agreed longer simply
    re-derive the same tokens next pass — wasteful, never wrong, and the
    classic single-sequence latency case (b=1) loses nothing. Throughput
    gain ≈ (mean acceptance + 1) / (1 + γ·cost_draft/cost_target); a draft
    that rarely agrees makes this SLOWER than greedy_generate — measure
    acceptance before deploying a draft.
    """
    b, p, total, d_cache, t_cache, t_logits, buf = _spec_setup(
        draft_params, target_params, prompt_tokens, cfg_draft, cfg_target,
        max_new_tokens=max_new_tokens, gamma=gamma, max_len=max_len,
        plain_decoder="greedy_generate",
    )
    buf = buf.at[:, p].set(jnp.argmax(t_logits, axis=-1).astype(jnp.int32))
    # Invariant at the top of each pass: n_done tokens emitted; both caches
    # hold positions 0..L-1 where L = p + n_done - 1; the newest emitted
    # token sits at buf[:, L] and has not been fed to either model yet.

    def cond(state):
        _, n_done, _, _ = state
        return n_done < max_new_tokens

    def body(state):
        buf, n_done, d_cache, t_cache = state
        L = p + n_done - 1
        pending = lax.dynamic_slice(buf, (0, L), (b, 1))[:, 0]

        # Draft rollout: γ+1 steps. Step j feeds the token at position L+j;
        # steps 0..γ-1 produce the proposals d_1..d_γ, and the extra step
        # feeds d_γ so the draft cache covers position L+γ — required when
        # every proposal is accepted (next pass starts at L+γ+1).
        def droll(carry, j):
            tok, cache = carry
            logits, cache = decode_step(
                draft_params, tok[:, None], cache, L + j, cfg_draft
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, cache), nxt

        (_, d_cache), props = lax.scan(
            droll, (pending, d_cache), jnp.arange(gamma + 1)
        )
        drafts = props[:gamma].T  # [b, γ]; d_j = drafts[:, j-1]

        # Verify: target scores [pending, d_1..d_γ] at positions L..L+γ in
        # one chunk; t_preds[:, j-1] is the target's choice for buf[L+j].
        chunk = jnp.concatenate([pending[:, None], drafts], axis=1)
        v_logits, t_cache = decode_chunk(
            target_params, chunk, t_cache, L, cfg_target
        )
        t_preds = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)  # [b, γ+1]

        # Longest agreeing prefix per row, then batch-min (lockstep).
        agree = drafts == t_preds[:, :gamma]
        row_accept = jnp.where(
            agree.all(axis=1), gamma, jnp.argmin(agree, axis=1)
        )
        accept = jnp.min(row_accept)

        # Emit t_1..t_{accept+1}. Writing the whole γ+1 prediction row is
        # safe: positions past the acceptance point are exactly the ones the
        # next pass rewrites (L' + 1 = L + accept + 2), and the final slice
        # never reaches past the last genuinely emitted token.
        buf = lax.dynamic_update_slice(buf, t_preds, (0, L + 1))
        return buf, n_done + accept + 1, d_cache, t_cache

    buf, _, _, _ = lax.while_loop(
        cond, body, (buf, jnp.int32(1), d_cache, t_cache)
    )
    return buf[:, : p + max_new_tokens]


@partial(
    jax.jit,
    static_argnames=("cfg_draft", "cfg_target", "max_new_tokens", "gamma", "max_len"),
)
def speculative_sample_generate(draft_params, target_params, prompt_tokens,
                                key, cfg_draft: LlamaConfig,
                                cfg_target: LlamaConfig, *,
                                max_new_tokens: int, gamma: int = 4,
                                temperature=1.0, max_len: int | None = None):
    """SAMPLED speculative decoding (the accept/resample algorithm of
    speculative sampling — PAPERS.md; `speculative_generate` above is its
    greedy special case). Per pass: the draft samples γ proposals
    autoregressively at `temperature`; the target scores the chunk in one
    decode_chunk forward; proposal d_j is accepted with probability
    min(1, p_j(d_j)/q_j(d_j)), the first rejection resamples from
    norm(max(0, p_j − q_j)), and a fully-accepted pass samples one extra
    token from p_{γ+1}. The emitted sequence is distributed EXACTLY as
    target-only ancestral sampling at the same temperature — the draft
    decides speed, never the distribution.

    Batch rows advance in lockstep by the BATCH-MINIMUM acceptance (same
    trade as speculative_generate): the token at the boundary position is
    per-row correct — rows that accepted further keep their accepted draft
    token, rows that rejected there get the residual resample — and every
    later position is rewritten by the next pass before it can be emitted.
    `temperature` is traced; the whole generation is ONE jitted program.
    """
    temp = jnp.maximum(temperature, 1e-6)
    b, p, total, d_cache, t_cache, t_logits, buf = _spec_setup(
        draft_params, target_params, prompt_tokens, cfg_draft, cfg_target,
        max_new_tokens=max_new_tokens, gamma=gamma, max_len=max_len,
        plain_decoder="sample_generate",
    )
    key, k0 = jax.random.split(key)
    buf = buf.at[:, p].set(
        jax.random.categorical(k0, t_logits / temp).astype(jnp.int32)
    )
    # Same invariant as speculative_generate: n_done emitted, caches cover
    # 0..L-1, newest emitted token at buf[:, L] not yet fed to either model.

    def cond(state):
        _, n_done, _, _, _ = state
        return n_done < max_new_tokens

    def body(state):
        buf, n_done, d_cache, t_cache, key = state
        key, k_draft, k_accept, k_res, k_extra = jax.random.split(key, 5)
        L = p + n_done - 1
        pending = lax.dynamic_slice(buf, (0, L), (b, 1))[:, 0]

        # Draft rollout, γ+1 steps (the extra step keeps the draft cache
        # covering L+γ for the all-accepted case), SAMPLING each proposal
        # and keeping its full logits row for the acceptance ratio.
        def droll(carry, inputs):
            j, step_key = inputs
            tok, cache = carry
            logits, cache = decode_step(
                draft_params, tok[:, None], cache, L + j, cfg_draft
            )
            nxt = jax.random.categorical(step_key, logits / temp)
            return (nxt.astype(jnp.int32), cache), (nxt.astype(jnp.int32), logits)

        (_, d_cache), (props, q_logits) = lax.scan(
            droll,
            (pending, d_cache),
            (jnp.arange(gamma + 1), jax.random.split(k_draft, gamma + 1)),
        )
        drafts = props[:gamma].T  # [b, γ]
        q_probs = jax.nn.softmax(
            q_logits[:gamma].transpose(1, 0, 2) / temp, axis=-1
        )  # [b, γ, V]

        chunk = jnp.concatenate([pending[:, None], drafts], axis=1)
        v_logits, t_cache = decode_chunk(
            target_params, chunk, t_cache, L, cfg_target
        )
        p_probs = jax.nn.softmax(v_logits / temp, axis=-1)  # [b, γ+1, V]

        # Acceptance: d_j accepted with prob min(1, p_j(d_j)/q_j(d_j)).
        p_at_draft = jnp.take_along_axis(
            p_probs[:, :gamma], drafts[..., None], axis=-1
        )[..., 0]
        q_at_draft = jnp.take_along_axis(
            q_probs, drafts[..., None], axis=-1
        )[..., 0]
        ratio = p_at_draft / jnp.maximum(q_at_draft, 1e-30)
        u = jax.random.uniform(k_accept, (b, gamma))
        # Strict <: uniform() can return exactly 0.0, and 0.0 <= 0.0 would
        # accept a token the target gives ZERO probability (visible in the
        # greedy limit, where disagreeing proposals underflow to p=0).
        accepted = u < ratio
        row_accept = jnp.where(
            accepted.all(axis=1), gamma, jnp.argmin(accepted, axis=1)
        )
        accept = jnp.min(row_accept)

        # Boundary token at position L+1+accept, per row:
        # - rows still accepting there keep their draft token;
        # - rows rejecting there resample from the residual
        #   norm(max(0, p − q)) (+eps so an exact p==q tie — a
        #   probability-zero rejection — stays finite);
        # - when every row accepted everything (accept == γ), sample the
        #   bonus token from p_{γ+1}.
        idx = jnp.minimum(accept, gamma - 1)
        p_at = lax.dynamic_index_in_dim(p_probs, accept, 1, keepdims=False)
        q_at = lax.dynamic_index_in_dim(q_probs, idx, 1, keepdims=False)
        residual = jnp.clip(p_at - q_at, 0.0, None)
        resample = jax.random.categorical(
            k_res, jnp.log(residual + 1e-30)
        ).astype(jnp.int32)
        extra = jax.random.categorical(
            k_extra, jnp.log(p_at + 1e-30)
        ).astype(jnp.int32)
        rejected_token = jnp.where(accept == gamma, extra, resample)
        draft_token = lax.dynamic_index_in_dim(
            drafts, idx, 1, keepdims=False
        )
        final = jnp.where(row_accept > accept, draft_token, rejected_token)

        # Emit d_1..d_accept then `final`; junk past the boundary is
        # rewritten by the next pass before it can be emitted (same
        # argument as speculative_generate's whole-row write).
        row = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1
        )
        row = jnp.where(
            jnp.arange(gamma + 1)[None, :] == accept, final[:, None], row
        )
        buf = lax.dynamic_update_slice(buf, row, (0, L + 1))
        return buf, n_done + accept + 1, d_cache, t_cache, key

    buf, _, _, _, _ = lax.while_loop(
        cond, body, (buf, jnp.int32(1), d_cache, t_cache, key)
    )
    return buf[:, : p + max_new_tokens]


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "max_len"))
def greedy_generate(params, prompt_tokens, cfg: LlamaConfig, *,
                    max_new_tokens: int, max_len: int | None = None,
                    eos_id=None):
    """Whole-generation greedy decode as ONE jitted program: batched prefill
    then a lax.scan over decode steps, token selection included. One device
    dispatch serves the entire generation — the per-step host round-trip
    that dominates a Python decode loop (milliseconds per token on a
    networked device) disappears. Returns [b, prompt + max_new_tokens].

    `eos_id` (None = off): a row that emits it has every LATER position
    pinned to eos_id — the fused scan's shape is static, so "stopping" is
    per-row pinning, not early exit (the saved work would be a partial
    scan's; batched serving pads to the longest row anyway). The value is
    traced: changing eos ids never recompiles.
    `generate()` below is the step-by-step reference implementation."""
    b, prompt_len = prompt_tokens.shape
    max_len = resolve_cache_len(prompt_len + max_new_tokens, max_len)
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt_tokens, cache, cfg)

    def body(carry, i):
        logits, cache, done = carry
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_id is not None:
            token = jnp.where(done, eos_id, token)
            done = done | (token == eos_id)
        logits, cache = decode_step(
            params, token[:, None], cache, prompt_len + i, cfg
        )
        return (logits, cache, done), token

    _, new_tokens = lax.scan(
        body,
        (logits, cache, jnp.zeros((b,), bool)),
        jnp.arange(max_new_tokens),
    )
    return jnp.concatenate([prompt_tokens, new_tokens.T], axis=1)


def nucleus_mask(scaled, top_p):
    """Top-p (nucleus) truncation: keep the smallest logit-sorted prefix
    whose cumulative probability reaches top_p. A token survives when the
    mass STRICTLY BEFORE it is < top_p — this always keeps the argmax and
    includes the token that crosses the threshold. `top_p` broadcasts
    against the leading dims (a scalar, or [b] -> pass [b, 1]); 1.0 masks
    nothing bit-exactly. The ONE nucleus rule — sample_generate and both
    serving engines share it."""
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    kept = jnp.where(mass_before < top_p, sorted_desc, jnp.inf)
    cutoff = jnp.min(kept, axis=-1, keepdims=True)
    return jnp.where(scaled < cutoff, NEG_INF_LOGIT, scaled)


def sample_generate(params, prompt_tokens, key, cfg: LlamaConfig, *,
                    max_new_tokens: int, temperature=1.0, top_k: int = 0,
                    top_p=None, max_len: int | None = None, eos_id=None):
    """Stochastic generation, fully jitted like greedy_generate: temperature
    scaling plus optional top-k and/or nucleus (top-p) truncation, sampled
    with jax.random (counter-based PRNG — same key, same output, any
    device). `temperature` and the top_p VALUE are traced scalars (sweeping
    settings never recompiles); `top_k` is static (it changes shapes) and
    `top_p=None` statically omits the nucleus block. With both set, top-k
    applies first, then the nucleus is taken within the surviving set — the
    usual composition. `eos_id` pins a row's positions after its first eos
    (see greedy_generate). Returns [b, prompt + max_new_tokens]."""
    if isinstance(top_p, (int, float)) and not 0.0 < top_p <= 1.0:
        # top_p=0 would otherwise mask EVERY logit (empty nucleus) and
        # degenerate to uniform sampling over the vocab — the opposite of
        # what a caller passing 0 ("basically greedy") means. Validated
        # HERE, outside jit, where top_p is still a python number (inside
        # the jitted impl it is a tracer); a traced top_p from a caller's
        # own jit is their contract to keep in range.
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    return _sample_generate_jit(
        params, prompt_tokens, key, cfg, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, max_len=max_len,
        eos_id=eos_id,
    )


@partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "top_k", "max_len")
)
def _sample_generate_jit(params, prompt_tokens, key, cfg: LlamaConfig, *,
                         max_new_tokens: int, temperature, top_k: int,
                         top_p, max_len: int | None, eos_id):
    b, prompt_len = prompt_tokens.shape
    max_len = resolve_cache_len(prompt_len + max_new_tokens, max_len)
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt_tokens, cache, cfg)

    def pick(step_key, logits):
        scaled = logits / jnp.maximum(temperature, 1e-6)
        if top_k > 0:
            kth = lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, NEG_INF_LOGIT, scaled)
        if top_p is not None:
            scaled = nucleus_mask(scaled, top_p)
        return jax.random.categorical(step_key, scaled).astype(jnp.int32)

    def body(carry, step_key):
        logits, cache, pos, done = carry
        token = pick(step_key, logits)
        if eos_id is not None:
            token = jnp.where(done, eos_id, token)
            done = done | (token == eos_id)
        logits, cache = decode_step(params, token[:, None], cache, pos, cfg)
        return (logits, cache, pos + 1, done), token

    step_keys = jax.random.split(key, max_new_tokens)
    _, new_tokens = lax.scan(
        body,
        (logits, cache, jnp.int32(prompt_len), jnp.zeros((b,), bool)),
        step_keys,
    )
    return jnp.concatenate([prompt_tokens, new_tokens.T], axis=1)


def generate(params, prompt_tokens, cfg: LlamaConfig, *, max_new_tokens: int,
             max_len: int | None = None):
    """Greedy autoregressive generation: one batched prefill pass over the
    prompt, then jitted single-token decode steps with the cache donated
    (updated in place) and the position carried as a traced scalar — one
    compile each for prefill and decode serves any lengths.
    Returns [b, prompt + max_new_tokens] int32.
    """
    b, prompt_len = prompt_tokens.shape
    max_len = resolve_cache_len(prompt_len + max_new_tokens, max_len)
    cache = init_cache(cfg, b, max_len)
    step = jax.jit(partial(decode_step, cfg=cfg), donate_argnums=(2,))

    logits, cache = jax.jit(partial(prefill, cfg=cfg), donate_argnums=(2,))(
        params, prompt_tokens, cache
    )
    tokens = prompt_tokens
    for i in range(max_new_tokens):
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        tokens = jnp.concatenate([tokens, next_token], axis=1)
        if i + 1 < max_new_tokens:
            logits, cache = step(
                params, next_token, cache, jnp.int32(prompt_len + i)
            )
    return tokens


# ---------------------------------------------------------------- training

def loss_fn(params, batch, cfg: LlamaConfig, *, mesh: Mesh | None = None):
    """Next-token cross-entropy. batch = {"tokens": [b, t+1] int32}."""
    tokens = batch["tokens"]
    logits = forward(params, tokens[:, :-1], cfg, mesh=mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: LlamaConfig, optimizer, *, mesh: Mesh | None = None,
                    accum_steps: int = 1):
    """Returns `train_step(params, opt_state, batch) -> (params, opt_state,
    loss)` — pure, jittable; shard via jit's in_shardings or device_put on
    the arguments (GSPMD propagates; grads of tp-sharded params come out
    tp-sharded, dp reduction is the implicit psum from the mean loss).

    `accum_steps > 1` splits the batch's leading dim into that many
    microbatches, accumulates gradients in float32 over a lax.scan, and
    applies ONE optimizer update — the effective-batch lever when
    activations for the full batch don't fit HBM (composes with
    cfg.remat, which shrinks depth-wise residency the same way this
    shrinks batch-wise)."""

    def train_step(params, opt_state, batch):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, cfg, mesh=mesh
            )
        else:
            b = batch["tokens"].shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch size {b} not divisible by accum_steps={accum_steps}"
                )
            # Microbatch the WHOLE batch tree, not just "tokens": any field
            # loss_fn grows later (a loss mask, say) must split identically
            # or the accum path would silently train on different data.
            micro = jax.tree.map(
                lambda x: x.reshape(accum_steps, b // accum_steps, *x.shape[1:]),
                batch,
            )

            def accumulate(carry, micro_batch):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, micro_batch, cfg, mesh=mesh
                )
                grad_sum = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), grad_sum, grads
                )
                return (loss_sum + loss, grad_sum), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            (loss_sum, grad_sum), _ = lax.scan(
                accumulate, (jnp.float32(0), zeros), micro
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / accum_steps).astype(p.dtype), grad_sum, params
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return train_step
