"""Model payloads shipped with the framework.

The reference ships no model code (SURVEY.md §2 census) — but its BASELINE
configs 3–5 (MNIST train, ICI allreduce, Llama-class inference through
Execute) need a real model to exercise the TPU path, and the framework's own
capstone benchmark payloads live here rather than being pasted into test
strings. Everything is pure JAX (jit/NamedSharding/shard_map), bfloat16 on
the matmul path, static shapes.
"""

from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    decode_chunk,
    decode_step,
    forward,
    generate,
    greedy_generate,
    init_cache,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
    prefill,
    sample_generate,
    speculative_generate,
    speculative_sample_generate,
)
from bee_code_interpreter_fs_tpu.models.hf_convert import from_hf_state_dict
from bee_code_interpreter_fs_tpu.models.rolling import (
    init_rolling_cache,
    rolling_decode_logits,
    rolling_decode_step,
    rolling_greedy_generate,
)
from bee_code_interpreter_fs_tpu.models.quant import (
    quantize4_params,
    quantize_params,
    quantized4_param_specs,
    quantized_nbytes,
    quantized_param_specs,
)
from bee_code_interpreter_fs_tpu.models.beam import beam_generate
from bee_code_interpreter_fs_tpu.models.lora import (
    init_lora,
    lora_param_specs,
    lora_wrap,
    make_lora_train_step,
    merge_lora,
    multi_lora_wrap,
    stack_loras,
)
from bee_code_interpreter_fs_tpu.models.paged import PagedServingEngine
from bee_code_interpreter_fs_tpu.models.serving import ServingEngine
from bee_code_interpreter_fs_tpu.models.spec_serving import (
    PagedSpeculativeServingEngine,
    SpeculativeServingEngine,
)

__all__ = [
    "LlamaConfig",
    "decode_chunk",
    "decode_step",
    "forward",
    "from_hf_state_dict",
    "generate",
    "greedy_generate",
    "init_cache",
    "init_params",
    "init_rolling_cache",
    "rolling_decode_logits",
    "rolling_decode_step",
    "rolling_greedy_generate",
    "loss_fn",
    "make_train_step",
    "param_specs",
    "prefill",
    "sample_generate",
    "speculative_generate",
    "speculative_sample_generate",
    "quantize4_params",
    "quantize_params",
    "quantized4_param_specs",
    "quantized_nbytes",
    "quantized_param_specs",
    "beam_generate",
    "init_lora",
    "lora_param_specs",
    "lora_wrap",
    "make_lora_train_step",
    "merge_lora",
    "multi_lora_wrap",
    "stack_loras",
    "PagedServingEngine",
    "ServingEngine",
    "PagedSpeculativeServingEngine",
    "SpeculativeServingEngine",
]
