"""Paged KV cache — block-pool attention for the serving engine.

The dense `ServingEngine` reserves a full `[max_len]` cache row per slot;
a slot serving a 40-token chat burns the same HBM as one serving a
4k-token document. This module stores K/V as a POOL of fixed-size blocks
(`[L, n_blocks, block_size, n_kv, hd]`) plus a per-slot block table
mapping logical positions to physical blocks — the vLLM memory model,
re-shaped for XLA:

- **Static shapes, gather-based reads.** A slot's logical cache is
  `pool[tables[slot]]` — one gather per layer, the same HBM traffic
  attention's read was already paying, so XLA's fusion keeps the decode
  step's cost profile while the POOL is sized for the traffic's actual
  token residency, not `n_slots × max_len`.
- **Frontier writes are per-slot scatters** at `(table[pos//bs], pos%bs)`;
  the allocator guarantees no two slots share a block, so scatter
  collisions cannot occur.
- **Reservation admission.** A request reserves its worst-case block count
  (`ceil((prompt+max_new)/block_size)`) up front; if the pool can't hold
  it, admission waits for retirements — no mid-flight exhaustion and no
  preemption machinery. Utilization still beats dense slots because the
  reservation tracks each REQUEST's need instead of a global max_len.
  (Lazy growth + preemption would reclaim the gap between reservation and
  actual use; deliberately out of scope here.)
- Prefill lands in a block-aligned contiguous scratch, then one scatter
  installs the whole prompt's blocks — admission stays O(bucket²) like
  the dense engine.
- **Registered prefixes share physical blocks.** A prefix's full blocks
  install once per engine; every sharing request's table points at the
  same ids, with private copy-on-write blocks from the frontier (partial
  last prefix block + suffix + generation). N sharers cost ~1x prefix +
  Nx suffix of pool residency — the block-table win the dense engine's
  per-slot prefix copy cannot express. Shared blocks pin while the
  prefix is registered (and in use); `unregister_prefix` reclaims them.

Everything the dense engine verifies holds here too (the test suite runs
the same token-exactness matrix against both): greedy == greedy_generate,
prefix caching, per-request sampling with schedule-independent streams.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from bee_code_interpreter_fs_tpu.models.quant import quantize_kv
from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _cached_gqa_attention,
    _rms_norm,
    _w,
    decode_chunk,
    decode_valid_mask,
    init_cache,
    transformer_block,
)
from bee_code_interpreter_fs_tpu.models.serving import (
    Request,
    ServingEngine,
    _burst_scan,
    _kv_write_read,
    _chunked_scratch_prefill,
    _prefill_scratch,
    _prefill_scratch_prefixed,
)

__all__ = ["PagedServingEngine"]


def _perslot_decode_step_paged(params, tokens, pool, tables, pos, active,
                               cfg: LlamaConfig):
    """One decode step over the block pool: write each slot's K/V at its
    frontier block/offset, then attend against the gathered logical cache.
    tokens [b, 1]; tables [b, max_blocks]; pos [b].

    INACTIVE slots must not write through their table: a retired slot's
    blocks may already belong to another request (the dense engine's
    harmless idle frontier rewrite becomes cross-request corruption here).
    They scatter into the pool's dedicated TRASH block (the last physical
    block, never allocated) instead — same static shapes, no branches."""
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    quant = "kq" in pool  # int8 pool (engine kv_quant=True)
    b, max_blocks = tables.shape
    ref = pool["kq"] if quant else pool["k"]
    bs = ref.shape[2]
    trash = ref.shape[1] - 1
    logical = max_blocks * bs
    valid = decode_valid_mask(pos, logical, cfg)[:, None, None, None, :]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, trash)
    off = pos % bs
    x = params["embed"].astype(dt)[tokens]

    def gathered(c):
        return c[tables].reshape(b, logical, *c.shape[2:])

    pool_keys, write_read = _kv_write_read(
        quant, lambda c, x: c.at[blk, off].set(x), gathered, dt
    )

    def layer(x, inputs):
        lp = inputs[0]
        cs = inputs[1:]
        cell = {}

        def attn_fn(q, k, v):
            new, keys, vals = write_read(cs, k[:, 0], v[:, 0])
            cell["kv"] = new
            return _cached_gqa_attention(q, keys, vals, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    x, new_leaves = lax.scan(
        layer, x, (params["layers"],) + tuple(pool[k] for k in pool_keys)
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, dict(zip(pool_keys, new_leaves))


@partial(jax.jit,
         static_argnames=("cfg", "steps", "eos_id", "with_logprobs",
                          "with_top_p", "with_penalties"),
         donate_argnames=("pool",))
def _decode_burst_paged(params, pool, tables, pos, last_tok, remaining,
                        active, temp, keys, top_p, presence, frequency,
                        counts, cfg: LlamaConfig,
                        steps: int, eos_id, with_logprobs: bool = False,
                        with_top_p: bool = False,
                        with_penalties: bool = False):
    """The paged twin of serving._decode_burst: same carry, same sampling
    stream, decode steps against the block pool (tables are constant for a
    burst — reservation admission pre-allocates every block a request can
    touch)."""

    def step_fn(pool, tokens, pos, active):
        return _perslot_decode_step_paged(
            params, tokens, pool, tables, pos, active, cfg
        )

    return _burst_scan(step_fn, pool, pos, last_tok, remaining, active,
                       temp, keys, steps, eos_id, with_logprobs,
                       top_p if with_top_p else None,
                       (presence, frequency, counts) if with_penalties
                       else None)


@partial(jax.jit, donate_argnames=("pool",))
def _pool_install_quant(pool, kv, blk_ids):
    """Quantize a DENSE block-aligned scratch and scatter it into the int8
    pool (prefill stays exact; only storage quantizes — mirrors the dense
    engine's _install_row_quant)."""
    L, _, T = kv["k"].shape[:3]
    bs = pool["kq"].shape[2]
    nb = T // bs
    kq, ks = quantize_kv(kv["k"])
    vq, vs = quantize_kv(kv["v"])

    def blocked(a):
        return a.reshape(L, nb, bs, *a.shape[3:])

    return {
        "kq": pool["kq"].at[:, blk_ids].set(blocked(kq)),
        "ks": pool["ks"].at[:, blk_ids].set(blocked(ks)),
        "vq": pool["vq"].at[:, blk_ids].set(blocked(vq)),
        "vs": pool["vs"].at[:, blk_ids].set(blocked(vs)),
    }


@partial(jax.jit, donate_argnames=("pool",))
def _pool_install(pool, kv, blk_ids):
    """Scatter a block-aligned scratch ([L, 1, nb*bs, ...]) into the pool
    at physical blocks `blk_ids` [nb]."""
    L, _, T = kv["k"].shape[:3]
    bs = pool["k"].shape[2]
    nb = T // bs
    k = kv["k"].reshape(L, nb, bs, *kv["k"].shape[3:])
    v = kv["v"].reshape(L, nb, bs, *kv["v"].shape[3:])
    return {
        "k": pool["k"].at[:, blk_ids].set(k),
        "v": pool["v"].at[:, blk_ids].set(v),
    }


class PagedServingEngine(ServingEngine):
    """Continuous batching over a paged block pool.

    `n_blocks * block_size` is the engine's total token residency; requests
    admit when their worst-case block reservation fits, else they wait for
    retirements. Semantics are identical to ServingEngine (same scheduler,
    same sampling streams, token-exact greedy)."""

    def __init__(self, params, cfg: LlamaConfig, *, block_size: int = 16,
                 n_blocks: int | None = None, **kwargs):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        pc = kwargs.get("prefill_chunk")
        if pc and pc % block_size:
            raise ValueError(
                f"prefill_chunk ({pc}) must be a multiple of block_size "
                f"({block_size}) so chunk-aligned scratches stay "
                "block-aligned"
            )
        self.block_size = int(block_size)
        self._requested_blocks = n_blocks
        super().__init__(params, cfg, **kwargs)

    def _init_device_state(self):
        bs = self.block_size
        self.max_blocks = -(-self.max_len // bs)
        n_blocks = (
            int(self._requested_blocks) if self._requested_blocks is not None
            else self.n_slots * self.max_blocks  # dense-equivalent capacity
        )
        if n_blocks < self.max_blocks:
            raise ValueError(
                f"n_blocks={n_blocks} cannot hold even one max-size request "
                f"({self.max_blocks} blocks)"
            )
        cfg = self.cfg
        # +1: the last physical block is the TRASH block inactive slots
        # write into (see _perslot_decode_step_paged); never allocated.
        shape = (cfg.n_layers, n_blocks + 1, bs, cfg.n_kv_heads, cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        if self.kv_quant:
            sshape = shape[:-1] + (1,)
            self.pool = {
                "kq": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(sshape, jnp.float32),
                "vq": jnp.zeros(shape, jnp.int8),
                "vs": jnp.zeros(sshape, jnp.float32),
            }
        else:
            self.pool = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        self.tables = jnp.zeros((self.n_slots, self.max_blocks), jnp.int32)
        self._free: list[int] = list(range(n_blocks))
        self._slot_blocks: list[list[int]] = [[] for _ in range(self.n_slots)]
        # Which registered prefix (if any) each slot's table references —
        # shared prefix blocks are pinned while any slot uses them.
        self._slot_prefix: list[int | None] = [None] * self.n_slots

    # ------------------------------------------------------------ helpers

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def _blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def _pad_to_blocks(self, n: int) -> int:
        return self._blocks_for(n) * self.block_size

    # ---------------------------------------------------------- admission

    def _install(self, req: Request, i: int):
        n = req.prompt.size
        if req.prefix_id is not None:
            return self._install_prefixed(req, i)
        prompt_end = n
        need = self._blocks_for(prompt_end + req.max_new_tokens)
        if need > len(self._free):
            return None  # wait for retirements
        blks = [self._free.pop() for _ in range(need)]
        self._slot_blocks[i] = blks
        self.tables = self.tables.at[i, :need].set(
            jnp.asarray(blks, jnp.int32)
        )
        bl = self._bucket_len(n)
        pad_to = self._pad_to_blocks(bl)
        if (self.prefill_chunk is not None
                and pad_to > self.prefill_chunk
                and pad_to % self.prefill_chunk == 0):
            padded = self._padded_prompt(req.prompt, pad_to)
            last_logits, scratch = _chunked_scratch_prefill(
                self._req_params(req), jnp.asarray(padded),
                jnp.int32(n), self.cfg, self.prefill_chunk,
            )
        else:
            padded = self._padded_prompt(req.prompt, bl)
            last_logits, scratch = _prefill_scratch(
                self._req_params(req), jnp.asarray(padded), jnp.int32(n),
                self.cfg, pad_to,
            )
        self.pool = self._install_scratch(scratch, blks, pad_to, need)
        first = self._pick_first(req, last_logits, prompt_end)
        return first, prompt_end

    def _install_prefixed(self, req: Request, i: int):
        """Admission with a registered prefix, SHARING the prefix's full
        blocks across requests (the block-table version of vLLM's prefix
        caching): the prefix's `plen // block_size` full blocks are
        installed into the pool ONCE per engine and every sharing request's
        table points at the same physical ids; only the frontier — the
        prefix's partial last block plus the request's suffix and
        generation span — occupies private copy-on-write blocks. Pool
        residency for N sharing requests is ~1x prefix + Nx suffix instead
        of Nx (prefix + suffix).

        Generation can never corrupt a shared block: decode writes land at
        pos >= prompt_end >= shared_tokens, and pos // block_size >=
        shared_nb indexes past the shared span of the table."""
        pf = self._prefixes[req.prefix_id]
        plen, n = pf["len"], req.prompt.size
        bs = self.block_size
        shared_nb = plen // bs
        shared_tok = shared_nb * bs
        prompt_end = plen + n
        need_priv = self._blocks_for(
            prompt_end + req.max_new_tokens
        ) - shared_nb
        alloc_shared = shared_nb if "pool_blocks" not in pf else 0
        if need_priv + alloc_shared > len(self._free):
            return None  # wait for retirements
        install = _pool_install_quant if self.kv_quant else _pool_install
        if alloc_shared:
            shared = [self._free.pop() for _ in range(shared_nb)]
            self.pool = install(
                self.pool,
                {"k": pf["k"][:, :, :shared_tok],
                 "v": pf["v"][:, :, :shared_tok]},
                jnp.asarray(shared, jnp.int32),
            )
            pf["pool_blocks"] = shared
        blks = [self._free.pop() for _ in range(need_priv)]
        self._slot_blocks[i] = blks  # private only; shared pins via prefix
        table = list(pf.get("pool_blocks", ())) + blks
        self.tables = self.tables.at[i, : len(table)].set(
            jnp.asarray(table, jnp.int32)
        )
        self._slot_prefix[i] = req.prefix_id
        pf["active_users"] = pf.get("active_users", 0) + 1

        if n == 0:
            rem = plen - shared_tok
            if rem:
                # Copy-on-write frontier: the prefix's partial last block
                # becomes this request's first private block (padded copy
                # memoized per prefix — N sharers pay the pad once).
                if "aligned_rem" not in pf:
                    grow = ((0, 0), (0, 0), (0, bs - rem), (0, 0), (0, 0))
                    pf["aligned_rem"] = {
                        "k": jnp.pad(pf["k"][:, :, shared_tok:], grow),
                        "v": jnp.pad(pf["v"][:, :, shared_tok:], grow),
                    }
                self.pool = install(
                    self.pool, pf["aligned_rem"],
                    jnp.asarray(blks[:1], jnp.int32),
                )
            first = self._pick_first(req, pf["last_logits"], plen)
        else:
            bl = self._suffix_bucket(plen, n)
            pad_to = self._pad_to_blocks(plen + bl)
            padded = self._padded_prompt(req.prompt, bl)
            last_logits, scratch = _prefill_scratch_prefixed(
                self._req_params(req), pf["k"], pf["v"],
                jnp.asarray(padded), jnp.int32(n), self.cfg, pad_to,
            )
            # Install only the frontier: [shared_tok, ...) — the shared
            # span already lives in the pool. Trim to the private
            # reservation (bucket padding can overshoot it).
            t_inst = min(pad_to - shared_tok, need_priv * bs)
            frontier = {
                "k": scratch["k"][:, :, shared_tok:shared_tok + t_inst],
                "v": scratch["v"][:, :, shared_tok:shared_tok + t_inst],
            }
            self.pool = install(
                self.pool, frontier,
                jnp.asarray(blks[: t_inst // bs], jnp.int32),
            )
            first = self._pick_first(req, last_logits, prompt_end)
        return first, prompt_end

    def _install_scratch(self, scratch, blks, pad_to: int, need: int):
        """Scatter the prompt scratch into the reserved blocks (via the
        quantizing installer on an int8 pool). The bucket
        padding can overshoot the request's reservation (a short prompt in
        a big bucket with a tiny budget): trim to the reserved extent —
        everything real (the prompt itself) always fits inside it, because
        need covers prompt + max_new tokens."""
        bs = self.block_size
        t_inst = min(pad_to, need * bs)
        if t_inst < pad_to:
            scratch = {
                "k": scratch["k"][:, :, :t_inst],
                "v": scratch["v"][:, :, :t_inst],
            }
        install = _pool_install_quant if self.kv_quant else _pool_install
        return install(
            self.pool, scratch, jnp.asarray(blks[: t_inst // bs], jnp.int32)
        )

    def _on_retire(self, i: int) -> None:
        self._free.extend(self._slot_blocks[i])
        self._slot_blocks[i] = []
        pid = self._slot_prefix[i]
        if pid is not None:
            self._slot_prefix[i] = None
            pf = self._prefixes.get(pid)
            if pf is not None:
                pf["active_users"] -= 1

    def unregister_prefix(self, prefix_id: int) -> None:
        pf = self._prefixes.get(prefix_id)
        if pf is not None and pf.get("active_users", 0) > 0:
            raise ValueError(
                f"prefix {prefix_id} is referenced by {pf['active_users']} "
                "active slot(s); drain or cancel them first"
            )
        super().unregister_prefix(prefix_id)  # raises for unknown/queued
        if pf is not None and "pool_blocks" in pf:
            self._free.extend(pf["pool_blocks"])

    def stats(self) -> dict:
        out = super().stats()
        shared = sum(
            len(pf.get("pool_blocks", ()))
            for pf in self._prefixes.values()
        )
        total = (len(self._free) + shared
                 + sum(len(b) for b in self._slot_blocks))
        out.update({
            "free_blocks": len(self._free),
            "shared_prefix_blocks": shared,
            "total_blocks": total,
            "block_size": self.block_size,
        })
        return out

    # -------------------------------------------------------------- burst

    def _run_burst(self, with_logprobs: bool = False,
                   with_top_p: bool = False,
                   with_penalties: bool = False):
        (self.pool, self.pos, self.last_tok, self.remaining, self.active,
         toks, emitted, lps, counts) = _decode_burst_paged(
            self._params_for(self._slot_adapter), self.pool, self.tables,
            self.pos, self.last_tok,
            self.remaining, self.active, self.temp, self.keys, self.top_p,
            self.presence, self.frequency,
            self.counts if self.counts is not None else self._counts_dummy,
            self.cfg, self.steps_per_sync, self.eos_id, with_logprobs,
            with_top_p, with_penalties,
        )
        if counts is not None:
            self.counts = counts
        return toks, emitted, lps
