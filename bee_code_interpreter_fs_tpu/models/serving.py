"""Continuous-batching inference engine — slot-scheduled serving on TPU.

The reference project serves model workloads one Execute call at a time
(`/root/reference/src/code_interpreter/services/code_executor.py` runs each
request in its own sandbox); concurrent inference is purely
process-per-request. This module adds the TPU-native alternative for the
config-5 concurrency story (BASELINE.md): ONE resident model instance that
serves many requests by iteration-level (continuous) batching, the way
production LLM servers schedule — requests join and leave the running batch
at token boundaries instead of waiting for a full-batch generation to
drain.

TPU-first design constraints drive the shape of everything here:

- **Static shapes only.** The decode batch is a fixed bank of `n_slots`
  cache slots; "joining the batch" means writing a prompt's K/V into a free
  slot, not growing a dimension. Finished slots keep computing (masked)
  until the next sync — XLA never sees a dynamic batch.
- **Bucketed prefill.** Admission pads the prompt to a small set of bucket
  lengths, so prompt ingestion compiles once per bucket (not once per
  prompt length). Padded positions write garbage K/V beyond the prompt's
  true length — provably never attended, because a decode step at position
  p first overwrites slot p and only ever reads positions <= p.
- **Fused decode bursts.** Between scheduler syncs the engine runs
  `steps_per_sync` decode steps as one `lax.scan` program (one device
  dispatch), amortizing the host<->device round trip that dominates
  per-token dispatch on a networked accelerator (BASELINE.md: 5 663 vs
  190 tok/s for fused vs per-step on this rig). Per-slot sequence lengths
  ride through the whole model as a [n_slots] position vector (per-slot
  RoPE offsets + per-slot causal masks), and cache writes are per-slot
  scatters at each slot's own frontier.

Scheduling (admission, retirement, queueing) is host-side Python between
bursts; everything inside a burst is compiled. EOS and per-request token
budgets deactivate slots in-device so a burst never generates past a
request's end; deactivated slots are retired and refilled at the next sync.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bee_code_interpreter_fs_tpu.models.quant import (
    dequantize_kv,
    quantize_kv,
)
from bee_code_interpreter_fs_tpu.models.llama import (
    LlamaConfig,
    _cached_gqa_attention,
    _rms_norm,
    _w,
    decode_chunk,
    decode_valid_mask,
    init_cache,
    nucleus_mask,
    prefill,
    transformer_block,
)

__all__ = ["ServingEngine", "Request"]


@dataclass
class Request:
    """One queued generation request (host-side bookkeeping)."""

    rid: int
    prompt: np.ndarray  # [prompt_len] int32 (the suffix when prefix_id set)
    max_new_tokens: int
    prefix_id: int | None = None
    temperature: float = 0.0  # 0 = greedy
    seed: int | None = None
    adapter: str | None = None  # multi-LoRA adapter name (None = base)
    on_token: object = None  # callable(list[int]) | None — streaming sink
    want_logprobs: bool = False
    top_p: float = 1.0  # nucleus truncation (1.0 = off)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    generated: list = field(default_factory=list)
    logprobs: list = field(default_factory=list)


def _perslot_decode_step(params, tokens, cache, pos, cfg: LlamaConfig):
    """One decode step where every slot sits at its OWN position.

    tokens: [b, 1] int32; pos: [b] int32 — slot i's token is at global
    position pos[i]. The per-slot generalization of
    ``llama.decode_step`` (scalar pos): the causal mask, RoPE offset, and
    cache write are all vectors over the batch. Returns
    (logits [b, vocab] f32, updated cache).
    """
    dt = jnp.dtype(cfg.dtype)
    scale = cfg.head_dim ** -0.5
    quant = "kq" in cache  # int8 KV cache (engine kv_quant=True)
    max_len = (cache["kq"] if quant else cache["k"]).shape[2]
    # Slot i sees cache positions <= pos[i] (its own prefix + itself);
    # broadcast the [b, max] mask over [b, g, r, t, k].
    valid = decode_valid_mask(pos, max_len, cfg)[:, None, None, None, :]
    x = params["embed"].astype(dt)[tokens]
    bidx = jnp.arange(tokens.shape[0])

    # One layer body for both cache formats: only the row write and the
    # K/V handed to attention differ — the shared strategy factory keeps
    # the int8 recipe in ONE place for the dense and paged engines alike.
    # Per-slot scatter at each slot's own frontier (the [b] pos vector
    # rules out one dynamic_update_slice for the batch).
    cache_keys, write_read = _kv_write_read(
        quant, lambda c, x: c.at[bidx, pos].set(x), lambda c: c, dt
    )

    def layer(x, inputs):
        lp = inputs[0]
        cs = inputs[1:]
        cell = {}

        def attn_fn(q, k, v):
            new, keys, vals = write_read(cs, k[:, 0], v[:, 0])
            cell["kv"] = new
            return _cached_gqa_attention(q, keys, vals, valid, scale)

        x = transformer_block(x, lp, cfg, attn_fn, rope_offset=pos)
        return x, cell["kv"]

    x, new_leaves = lax.scan(
        layer, x, (params["layers"],) + tuple(cache[k] for k in cache_keys)
    )
    new_cache = dict(zip(cache_keys, new_leaves))
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ _w(params["lm_head"], dt)).astype(jnp.float32)
    return logits, new_cache


def _kv_write_read(quant: bool, write_at, read_tf, dt):
    """Build the per-layer KV (cache_keys, write_read) strategy shared by
    the dense and paged decode steps: `write_at(cache_leaf, value)` places
    the new token's K/V (row scatter vs block scatter) and `read_tf`
    produces the attention-readable view (identity vs block-table gather).
    With `quant`, values quantize at the write and dequantize AT THE READ —
    HBM streams int8 + scales and the multiply fuses into the attention
    contraction; the recipe exists exactly once for both engines."""
    if quant:
        keys = ("kq", "ks", "vq", "vs")

        def write_read(cs, k, v):
            ckq, cks, cvq, cvs = cs
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            new = (write_at(ckq, kq), write_at(cks, ks),
                   write_at(cvq, vq), write_at(cvs, vs))
            return new, dequantize_kv(
                read_tf(new[0]), read_tf(new[1]), dt
            ), dequantize_kv(read_tf(new[2]), read_tf(new[3]), dt)
    else:
        keys = ("k", "v")

        def write_read(cs, k, v):
            new = (write_at(cs[0], k), write_at(cs[1], v))
            return new, read_tf(new[0]), read_tf(new[1])

    return keys, write_read


def _sample_next(logits, temp, keys, pos, top_p=None):
    """Next token per slot: greedy where temp == 0, else a categorical draw
    whose key is fold_in(slot key, the sampled token's position) — the ONE
    definition of the engine's sampling stream (the paged engine's burst
    uses it too, so both engines are stream-identical). `top_p` ([b] or
    None — a STATIC distinction, compiled separately) truncates to the
    nucleus before drawing."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    subkeys = jax.vmap(jax.random.fold_in)(keys, pos + 1)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]
    if top_p is not None:
        scaled = nucleus_mask(scaled, top_p[:, None])
    sampled = jax.vmap(jax.random.categorical)(subkeys, scaled)
    return jnp.where(temp > 0, sampled.astype(jnp.int32), greedy)


def _burst_scan(step_fn, store, pos, last_tok, remaining, active, temp,
                keys, steps: int, eos_id, with_logprobs: bool,
                top_p=None, penalties=None):
    """The ONE burst loop body both engines run: step_fn produces logits and
    the updated KV store; everything else — the sampling stream, emit
    bookkeeping, budget/EOS masking — lives here so the dense and paged
    engines cannot drift.

    `penalties` (static None = off): (presence [b], frequency [b],
    counts [b, vocab] int32) — OpenAI-style repetition control. Penalties
    shape token CHOICE (greedy argmax included); reported logprobs stay
    raw-model, like temperature."""

    def one(carry, _):
        if penalties is None:
            store, pos, tok, remaining, active = carry
        else:
            store, pos, tok, remaining, active, counts = carry
        logits, store = step_fn(store, tok[:, None], pos, active)
        if penalties is None:
            choice_logits = logits
        else:
            presence, frequency = penalties
            choice_logits = (
                logits
                - presence[:, None] * (counts > 0)
                - frequency[:, None] * counts
            )
        nxt = _sample_next(choice_logits, temp, keys, pos, top_p)
        if with_logprobs:
            # Chosen-token log-prob under the RAW model distribution (the
            # OpenAI-style convention: temperature shapes sampling, not
            # the reported likelihoods).
            lp = jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=1
            )[:, 0]
        else:
            # Static no-logprob variant: no vocab-wide softmax in the hot
            # loop; the lane stays shape-stable as zeros.
            lp = jnp.zeros((logits.shape[0],), jnp.float32)
        tok = jnp.where(active, nxt, tok)
        emitted = active
        pos = pos + active.astype(jnp.int32)
        remaining = remaining - active.astype(jnp.int32)
        active = active & (remaining > 0)
        if eos_id is not None:
            active = active & (tok != eos_id)
        if penalties is None:
            return (store, pos, tok, remaining, active), (tok, emitted, lp)
        counts = counts.at[jnp.arange(tok.shape[0]), tok].add(
            emitted.astype(jnp.int32)
        )
        return (store, pos, tok, remaining, active, counts), (
            tok, emitted, lp
        )

    if penalties is None:
        carry0 = (store, pos, last_tok, remaining, active)
    else:
        presence, frequency, counts0 = penalties
        penalties = (presence, frequency)
        carry0 = (store, pos, last_tok, remaining, active, counts0)
    carry, (toks, emitted, lps) = lax.scan(one, carry0, None, length=steps)
    store, pos, tok, remaining, active = carry[:5]
    counts = carry[5] if len(carry) > 5 else None
    return store, pos, tok, remaining, active, toks, emitted, lps, counts


@partial(jax.jit,
         static_argnames=("cfg", "steps", "eos_id", "with_logprobs",
                          "with_top_p", "with_penalties"),
         donate_argnames=("cache",))
def _decode_burst(params, cache, pos, last_tok, remaining, active,
                  temp, keys, top_p, presence, frequency, counts,
                  cfg: LlamaConfig, steps: int, eos_id,
                  with_logprobs: bool = False, with_top_p: bool = False,
                  with_penalties: bool = False):
    """`steps` continuous-batching decode steps as ONE compiled program.

    Carry per slot: position, last emitted token, remaining token budget,
    active flag. Inactive slots still flow through the (static-shape)
    computation but are fully masked: their position doesn't advance, their
    token doesn't change, and their cache row only rewrites its own frontier
    with values nothing ever attends to.

    Per-slot sampling: `temp` [b] f32 (0 = greedy) and `keys` [b, 2]
    uint32 per-request PRNG keys. Each sampled token's randomness is
    `fold_in(key, position)` — the key never advances, so a request's
    stream depends only on its seed and token positions, not on scheduling
    (the same request replays identically whatever traffic shares the
    batch).

    Returns (cache, pos, last_tok, remaining, active, toks [steps, b],
    emitted [steps, b], lps [steps, b]) — toks[s, i] is a real generated
    token for slot i iff emitted[s, i]; lps[s, i] its model log-prob.
    """

    def step_fn(cache, tokens, pos, active):
        del active  # a dense slot's idle frontier rewrite is harmless
        return _perslot_decode_step(params, tokens, cache, pos, cfg)

    return _burst_scan(step_fn, cache, pos, last_tok, remaining, active,
                       temp, keys, steps, eos_id, with_logprobs,
                       top_p if with_top_p else None,
                       (presence, frequency, counts) if with_penalties
                       else None)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _admit(params, cache, tokens, slot, true_len, cfg: LlamaConfig):
    """Prefill one bucketed prompt and install it into cache slot `slot`.

    tokens: [1, bucket_len] (prompt right-padded to the bucket); `slot` and
    `true_len` are traced scalars, so one compile serves every admission at
    this bucket length. Returns (cache, last_logits) — the prompt's
    last-position logits, from which the host picks the first generated
    token (greedy or sampled per the request). K/V written for padded
    positions (>= true_len) are garbage by construction and provably never
    attended (see module doc).

    The scratch cache is BUCKET-sized, not max_len-sized, so prefill
    attention costs O(bucket²) rather than O(bucket·max_len); the slot
    row's tail beyond the bucket keeps its previous occupant's stale K/V,
    which is safe by the same overwrite-before-read invariant (a stale
    position j only becomes visible once pos >= j, and the decode step at
    pos == j rewrites it first).
    """
    bucket = tokens.shape[1]
    slot_cache = init_cache(cfg, 1, bucket)
    logits_all, slot_cache = decode_chunk(params, tokens, slot_cache, 0, cfg)
    last_logits = logits_all[0, true_len - 1]
    new_k = lax.dynamic_update_slice(
        cache["k"], slot_cache["k"], (0, slot, 0, 0, 0)
    )
    new_v = lax.dynamic_update_slice(
        cache["v"], slot_cache["v"], (0, slot, 0, 0, 0)
    )
    return {"k": new_k, "v": new_v}, last_logits


@partial(jax.jit, static_argnames=("cfg", "pad_to"))
def _prefill_scratch(params, tokens, true_len, cfg: LlamaConfig, pad_to: int):
    """Prefill a bucketed prompt into a BLOCK-ALIGNED contiguous scratch
    ([L, 1, pad_to, ...]); returns (last_logits, scratch kv)."""
    scratch = init_cache(cfg, 1, pad_to)
    logits_all, scratch = decode_chunk(params, tokens, scratch, 0, cfg)
    return logits_all[0, true_len - 1], scratch


@partial(jax.jit, static_argnames=("cfg", "pad_to"))
def _prefill_scratch_prefixed(params, pk, pv, tokens, true_len,
                              cfg: LlamaConfig, pad_to: int):
    """Prefix-cached variant: install the prefix K/V then chunk-prefill the
    suffix at rope offset plen, all in one block-aligned scratch."""
    plen = pk.shape[2]
    scratch = init_cache(cfg, 1, pad_to)
    scratch = {
        "k": lax.dynamic_update_slice(scratch["k"], pk, (0, 0, 0, 0, 0)),
        "v": lax.dynamic_update_slice(scratch["v"], pv, (0, 0, 0, 0, 0)),
    }
    logits_all, scratch = decode_chunk(params, tokens, scratch, plen, cfg)
    return logits_all[0, true_len - 1], scratch


@partial(jax.jit, static_argnames=("cfg", "chunk"))
def _chunked_scratch_prefill(params, tokens, true_len, cfg: LlamaConfig,
                             chunk: int):
    """Prefill a (bucketed, chunk-aligned) prompt in fixed-size chunks: a
    lax.scan feeds `chunk` tokens at a time against the growing scratch
    cache, so attention's score tensor peaks at O(chunk x bucket) instead
    of O(bucket^2) — the long-prompt admission path. Returns (last_logits
    [vocab] at true_len-1, scratch kv [L, 1, bucket, ...])."""
    bucket = tokens.shape[1]
    if bucket % chunk:
        raise ValueError(
            f"bucket {bucket} is not a multiple of prefill chunk {chunk} — "
            "the tail would silently never prefill"
        )
    n_chunks = bucket // chunk
    scratch = init_cache(cfg, 1, bucket)
    vocab = cfg.vocab_size

    def body(carry, i):
        scratch, out = carry
        chunk_toks = lax.dynamic_slice(tokens, (0, i * chunk), (1, chunk))
        logits, scratch = decode_chunk(params, chunk_toks, scratch,
                                       i * chunk, cfg)
        # The prompt's last real position lives in exactly one chunk.
        sel = (true_len - 1) // chunk == i
        out = jnp.where(sel, logits[0, (true_len - 1) % chunk], out)
        return (scratch, out), None

    (scratch, last_logits), _ = lax.scan(
        body, (scratch, jnp.zeros((vocab,), jnp.float32)),
        jnp.arange(n_chunks),
    )
    return last_logits, scratch


@partial(jax.jit, donate_argnames=("cache",))
def _install_row_quant(cache, scratch, slot):
    """Quantize a DENSE prefill scratch and install it into an int8 KV
    cache row: prompts prefill at full precision (exact logits for the
    first token), and only the stored cache pays the quantization."""
    kq, ks = quantize_kv(scratch["k"])
    vq, vs = quantize_kv(scratch["v"])
    at = (0, slot, 0, 0, 0)
    return {
        "kq": lax.dynamic_update_slice(cache["kq"], kq, at),
        "ks": lax.dynamic_update_slice(cache["ks"], ks, at),
        "vq": lax.dynamic_update_slice(cache["vq"], vq, at),
        "vs": lax.dynamic_update_slice(cache["vs"], vs, at),
    }


@partial(jax.jit, donate_argnames=("cache",))
def _install_row(cache, scratch, slot):
    """Install a contiguous scratch ([L, 1, T <= max_len, ...]) into dense
    cache row `slot` (the chunked-admission counterpart of _admit's
    in-jit install)."""
    return {
        "k": lax.dynamic_update_slice(
            cache["k"], scratch["k"], (0, slot, 0, 0, 0)
        ),
        "v": lax.dynamic_update_slice(
            cache["v"], scratch["v"], (0, slot, 0, 0, 0)
        ),
    }


# One compile per distinct prefix length, paid at registration time.
# prefill (not decode_chunk): it projects logits only at the LAST position,
# so registering a long system prompt never materializes a [plen, vocab]
# logits buffer it would immediately discard.
_prefix_prefill = jax.jit(prefill, static_argnames=("cfg",))


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _admit_prefixed(params, cache, pk, pv, tokens, slot, true_len,
                    cfg: LlamaConfig):
    """Admission with a cached prefix: install the prefix's precomputed K/V
    (positions 0..plen-1) and chunk-prefill only the SUFFIX at
    rope_offset=plen. One compile per (prefix length, suffix bucket) pair;
    the prefix forward itself was paid ONCE at register_prefix time no
    matter how many requests share it."""
    plen = pk.shape[2]
    bucket = tokens.shape[1]
    scratch = init_cache(cfg, 1, plen + bucket)
    scratch = {
        "k": lax.dynamic_update_slice(scratch["k"], pk, (0, 0, 0, 0, 0)),
        "v": lax.dynamic_update_slice(scratch["v"], pv, (0, 0, 0, 0, 0)),
    }
    logits_all, scratch = decode_chunk(params, tokens, scratch, plen, cfg)
    last_logits = logits_all[0, true_len - 1]
    new_k = lax.dynamic_update_slice(
        cache["k"], scratch["k"], (0, slot, 0, 0, 0)
    )
    new_v = lax.dynamic_update_slice(
        cache["v"], scratch["v"], (0, slot, 0, 0, 0)
    )
    return {"k": new_k, "v": new_v}, last_logits


@partial(jax.jit, donate_argnames=("cache",))
def _admit_prefix_only(cache, pk, pv, slot):
    """Admission of a request whose whole prompt IS a cached prefix: pure
    K/V installation — zero model FLOPs on the admission path."""
    new_k = lax.dynamic_update_slice(cache["k"], pk, (0, slot, 0, 0, 0))
    new_v = lax.dynamic_update_slice(cache["v"], pv, (0, slot, 0, 0, 0))
    return {"k": new_k, "v": new_v}


class ServingEngine:
    """Continuous-batching greedy serving over a fixed slot bank.

    >>> eng = ServingEngine(params, cfg, n_slots=4, max_len=512)
    >>> rid = eng.submit([1, 5, 9], max_new_tokens=32)
    >>> results = eng.run()          # {rid: np.ndarray of generated tokens}

    Tokens returned are the GENERATED continuation only (the prompt is the
    caller's). With `eos_id` set, generation stops at (and includes) the
    first eos token — matching `greedy_generate`'s pinning semantics
    truncated at the first eos.
    """

    def __init__(self, params, cfg: LlamaConfig, *, n_slots: int = 4,
                 max_len: int | None = None, steps_per_sync: int = 8,
                 prefill_buckets: tuple = (), eos_id: int | None = None,
                 seed: int = 0, adapters: dict | None = None,
                 lora_alpha: float = 16.0, prefill_chunk: int | None = None,
                 kv_quant: bool = False):
        """`adapters`: {name: lora tree (models/lora.init_lora shape)} —
        multi-tenant adapter serving. Every request picks one by name (or
        None for the bare base model); one resident base plus one stacked
        adapter bank serve them all in the same bursts, with index 0 the
        zero adapter so un-adapted rows compute the exact base model."""
        self.params = params
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len or cfg.max_seq_len)
        self.steps_per_sync = int(steps_per_sync)
        self.eos_id = eos_id
        self.kv_quant = bool(kv_quant)
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and not (
            1 <= self.prefill_chunk < self.max_len
        ):
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) must be in "
                f"[1, max_len={self.max_len}) — a chunk that can never "
                "fire is a misconfiguration"
            )
        if prefill_buckets:
            self.buckets = tuple(sorted(int(b) for b in prefill_buckets))
            if self.buckets[0] < 1 or self.buckets[-1] > self.max_len:
                raise ValueError(
                    f"prefill_buckets must lie in [1, max_len={self.max_len}]"
                    f", got {self.buckets}"
                )
        else:
            # Powers of two, topped by the largest admissible prompt length
            # (max_len - 1: at least one generated token must fit).
            pows = [b for b in (2 ** i for i in range(4, 32))
                    if b < self.max_len - 1]
            self.buckets = tuple(pows + [self.max_len - 1])
        if self.prefill_chunk is not None:
            # Chunked admission scans fixed-size chunks, so add chunk-
            # aligned bucket variants — but KEEP the original top bucket:
            # capacity never shrinks (an unaligned bucket simply routes
            # through the single-pass path).
            c = self.prefill_chunk
            aligned = {
                min(-(-b // c) * c, (self.max_len // c) * c)
                for b in self.buckets
            }
            aligned = {b for b in aligned if b > 0}
            self.buckets = tuple(sorted(aligned | {max(self.buckets)}))
        self._init_device_state()
        self.pos = jnp.zeros((self.n_slots,), jnp.int32)
        self.last_tok = jnp.zeros((self.n_slots,), jnp.int32)
        self.remaining = jnp.zeros((self.n_slots,), jnp.int32)
        self.active = jnp.zeros((self.n_slots,), bool)
        self._slot_req: list[Request | None] = [None] * self.n_slots
        self._queue: deque[Request] = deque()
        self._results: dict[int, np.ndarray] = {}
        self._logprob_results: dict[int, np.ndarray] = {}
        self._rid = itertools.count()
        self._prefixes: dict[int, dict] = {}
        self._prefix_id = itertools.count()
        self.temp = jnp.zeros((self.n_slots,), jnp.float32)
        self.top_p = jnp.ones((self.n_slots,), jnp.float32)
        self.presence = jnp.zeros((self.n_slots,), jnp.float32)
        self.frequency = jnp.zeros((self.n_slots,), jnp.float32)
        # [n_slots, vocab] i32, allocated lazily at the first penalized
        # admission — a no-penalty deployment never pays the residency.
        self.counts = None
        self._counts_dummy = jnp.zeros((self.n_slots, 1), jnp.int32)
        self.keys = jnp.zeros((self.n_slots, 2), jnp.uint32)
        self._base_seed = int(seed)
        self._lora_alpha = float(lora_alpha)
        self._stacked = None
        self._adapter_idx: dict = {None: 0}
        self._slot_adapter = np.zeros((self.n_slots,), np.int32)
        if adapters:
            from bee_code_interpreter_fs_tpu.models.lora import (
                stack_loras,
                zero_lora,
            )

            names = list(adapters)
            first = adapters[names[0]]["layers"]
            targets = tuple(first)
            for n in names[1:]:
                if tuple(adapters[n]["layers"]) != targets:
                    raise ValueError(
                        f"adapters must share one target set: {names[0]!r} "
                        f"has {targets}, {n!r} has "
                        f"{tuple(adapters[n]['layers'])} (pad the smaller "
                        "adapter with zero targets or retrain)"
                    )
            rank = next(iter(first.values()))["a"].shape[-1]
            zero = zero_lora(cfg, rank=rank, targets=targets)
            self._stacked = stack_loras(
                [zero] + [adapters[n] for n in names], targets=targets,
                alpha=self._lora_alpha,
            )
            self._adapter_idx.update(
                {n: i + 1 for i, n in enumerate(names)}
            )

    def _init_device_state(self):
        """Device-side KV state. The base engine holds one dense
        [n_slots, max_len] cache — int8-quantized per head-dim vector when
        kv_quant is on (the context-length-proportional HBM term halves);
        PagedServingEngine overrides with a block pool + tables."""
        if self.kv_quant:
            cfg = self.cfg
            shape = (cfg.n_layers, self.n_slots, self.max_len,
                     cfg.n_kv_heads, cfg.head_dim)
            sshape = shape[:-1] + (1,)
            self.cache = {
                "kq": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(sshape, jnp.float32),
                "vq": jnp.zeros(shape, jnp.int8),
                "vs": jnp.zeros(sshape, jnp.float32),
            }
        else:
            self.cache = init_cache(self.cfg, self.n_slots, self.max_len)

    # ------------------------------------------------------------- intake

    def register_prefix(self, tokens, adapter: str | None = None) -> int:
        """Prefill a shared prompt prefix ONCE and cache its K/V; requests
        submitted with the returned id skip the prefix's prefill entirely
        (the classic system-prompt amortization). Costs one [L, 1, plen]
        K/V buffer in device memory per registered prefix."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            raise ValueError("empty prefix")
        if tokens.size >= self.max_len:
            raise ValueError(
                f"prefix ({tokens.size}) leaves no room in max_len "
                f"{self.max_len}"
            )
        if adapter is not None and adapter not in self._adapter_idx:
            raise ValueError(f"unknown adapter {adapter!r}")
        plen = int(tokens.size)
        p = self._params_for([self._adapter_idx.get(adapter, 0)])
        if self.prefill_chunk is not None and plen > self.prefill_chunk:
            # Long system prompts are where chunked prefill matters most:
            # registration memory peaks at O(chunk x plen), not O(plen^2).
            c = self.prefill_chunk
            pad = -(-plen // c) * c
            padded = np.zeros((1, pad), np.int32)
            padded[0, :plen] = tokens
            row_logits, scratch = _chunked_scratch_prefill(
                p, jnp.asarray(padded), jnp.int32(plen), self.cfg, c
            )
            scratch = {
                "k": scratch["k"][:, :, :plen],
                "v": scratch["v"][:, :, :plen],
            }
        else:
            scratch = init_cache(self.cfg, 1, plen)
            batch_logits, scratch = _prefix_prefill(
                p, jnp.asarray(tokens[None, :]), scratch, self.cfg
            )
            row_logits = batch_logits[0]
        pid = next(self._prefix_id)
        self._prefixes[pid] = {
            "k": scratch["k"],
            "v": scratch["v"],
            "last_logits": np.asarray(row_logits, np.float32),
            "len": plen,
            "adapter": adapter,
        }
        return pid

    def unregister_prefix(self, prefix_id: int) -> None:
        """Release a registered prefix's device K/V (including any engine-
        side memos keyed off it, e.g. the paged engine's block-aligned
        copy), reclaiming its memory in a long-lived engine. Requests
        already ADMITTED with it copied what they needed and are
        unaffected; raises while QUEUED requests still reference it (they
        would crash at admission after the K/V is gone)."""
        if prefix_id not in self._prefixes:
            raise ValueError(f"unknown prefix_id {prefix_id}")
        users = [r.rid for r in self._queue if r.prefix_id == prefix_id]
        if users:
            raise ValueError(
                f"prefix {prefix_id} is referenced by queued request(s) "
                f"{users}; drain or cancel them first"
            )
        del self._prefixes[prefix_id]

    def submit(self, prompt, max_new_tokens: int,
               prefix_id: int | None = None, *, temperature: float = 0.0,
               seed: int | None = None, adapter: str | None = None,
               on_token=None, logprobs: bool = False,
               top_p: float = 1.0, presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0) -> int:
        """Queue a prompt (sequence of int token ids); returns request id.
        With `prefix_id`, `prompt` is the SUFFIX after that registered
        prefix (may be empty — the prefix alone is the prompt).

        `on_token` (callable taking a list[int]) streams the request's new
        tokens at every scheduler sync — burst-granular (up to
        steps_per_sync tokens per call), in order, concatenating to
        exactly the final result. Exceptions from a callback propagate out
        of step()/run() only after every slot's tokens are recorded and
        every other sink is delivered — a broken sink never corrupts any
        request's results (resume by calling run() again).
        `temperature` > 0 samples instead of greedy decoding; the request's
        random stream is `fold_in(key, token position)`, so with an explicit
        `seed` the output is reproducible regardless of what other traffic
        shares the batch or how the scheduler slices bursts (seed=None
        derives a key from the engine seed and the request id).
        `presence_penalty` / `frequency_penalty` follow the OpenAI
        convention: they count GENERATED tokens only (prompt and prefix
        text never feed the histogram), shape token choice (greedy argmax
        included), and leave reported logprobs raw-model."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if adapter is not None and adapter not in self._adapter_idx:
            raise ValueError(f"unknown adapter {adapter!r}")
        plen = 0
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(f"unknown prefix_id {prefix_id}")
            pf = self._prefixes[prefix_id]
            if pf["adapter"] != adapter:
                raise ValueError(
                    f"prefix {prefix_id} was registered under adapter "
                    f"{pf['adapter']!r}; request uses {adapter!r} — prefix "
                    "K/V is adapter-specific"
                )
            plen = pf["len"]
        elif prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if plen + prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix ({plen}) + prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds cache max_len {self.max_len}"
            )
        if (prefix_id is None and prompt.size > 0
                and prompt.size > max(self.buckets)):
            # Prefixed suffixes skip this gate: _suffix_bucket's exact-
            # remainder fallback (max_len - plen) holds any suffix the
            # total-length check above admitted, even when the caller
            # configured only small custom prefill_buckets.
            raise ValueError(
                f"prompt length {prompt.size} exceeds largest prefill "
                f"bucket {max(self.buckets)}"
            )
        rid = next(self._rid)
        self._queue.append(
            Request(rid, prompt, int(max_new_tokens), prefix_id,
                    float(temperature), seed, adapter, on_token,
                    bool(logprobs), float(top_p), float(presence_penalty),
                    float(frequency_penalty))
        )
        return rid

    def _suffix_bucket(self, plen: int, n: int) -> int:
        """Smallest bucket holding an n-token suffix beside a plen-token
        prefix; the exact remainder is the (rare, its own compile) fallback
        and holds n by submit's total-length check."""
        return next(
            (b for b in self.buckets if b >= n and plen + b <= self.max_len),
            self.max_len - plen,
        )

    @staticmethod
    def _padded_prompt(prompt: np.ndarray, bl: int) -> np.ndarray:
        padded = np.zeros((1, bl), np.int32)
        padded[0, : prompt.size] = prompt
        return padded

    def _bucket_len(self, n: int) -> int:
        plain = next((b for b in self.buckets if n <= b), None)
        if plain is None:
            raise ValueError(f"no bucket holds prompt of length {n}")
        c = self.prefill_chunk
        if c is not None and plain > c and plain % c:
            # An unaligned bucket above the chunk size routes through the
            # O(bucket^2) single-pass admit — exactly the long-prompt range
            # chunked prefill exists for. Prefer the smallest chunk-aligned
            # bucket that also holds the prompt; keep the unaligned bucket
            # only when no aligned one can (capacity never shrinks).
            aligned = next(
                (b for b in self.buckets
                 if n <= b and b > c and b % c == 0),
                None,
            )
            if aligned is not None:
                return aligned
        return plain

    def _params_for(self, ids) -> dict:
        """Base params, or the multi-adapter wrapped tree selecting adapter
        ids[i] for batch row i. The wrap rebuilds only composite-leaf dicts
        around the same arrays — structure is identical across calls, so
        the jitted programs never recompile on adapter churn."""
        if self._stacked is None:
            return self.params
        from bee_code_interpreter_fs_tpu.models.lora import multi_lora_wrap

        return multi_lora_wrap(
            self.params, self._stacked, jnp.asarray(ids, jnp.int32)
        )

    def _req_params(self, req: Request) -> dict:
        return self._params_for([self._adapter_idx[req.adapter]])

    def _req_key(self, req: Request):
        if req.seed is not None:
            return jax.random.PRNGKey(req.seed)
        return jax.random.fold_in(
            jax.random.PRNGKey(self._base_seed), req.rid
        )

    def _pick_first(self, req: Request, last_logits, prompt_end: int) -> int:
        """First generated token from admission logits: greedy, or sampled
        with the same fold_in(key, position) stream the burst continues.
        Records the token's model log-prob when the request asked for
        logprobs."""
        last_logits = jnp.asarray(last_logits)
        raw_logits = last_logits
        # Penalties count GENERATED tokens only (the OpenAI convention the
        # API names): at admission nothing has been generated, so the first
        # token's choice is unpenalized by construction.
        if req.temperature <= 0:
            # Device-side argmax: a greedy admission moves one scalar to
            # host, never the vocab-wide logits row.
            tok = int(jnp.argmax(last_logits))
        else:
            sub = jax.random.fold_in(self._req_key(req), prompt_end)
            scaled = last_logits / req.temperature
            if req.top_p < 1.0:
                scaled = nucleus_mask(scaled[None, :], req.top_p)[0]
            tok = int(jax.random.categorical(sub, scaled))
        if req.want_logprobs:
            req.logprobs.append(
                float(jax.nn.log_softmax(raw_logits)[tok])
            )
        return tok

    # ---------------------------------------------------------- scheduling

    def _retire(self):
        active_np = np.asarray(self.active)
        for i in range(self.n_slots):
            req = self._slot_req[i]
            if req is not None and not active_np[i]:
                self._record_result(req)
                self._slot_req[i] = None
                self._on_retire(i)

    def _install(self, req: Request, i: int):
        """Prefill `req`'s prompt into slot `i`'s KV storage. Returns
        (first_token, prompt_end), or None when the engine cannot place the
        request right now (paged engine out of blocks) — the caller
        requeues it and stops admitting."""
        n = req.prompt.size
        install = _install_row_quant if self.kv_quant else _install_row
        if req.prefix_id is not None:
            pf = self._prefixes[req.prefix_id]
            plen = pf["len"]
            if n == 0:
                if self.kv_quant:
                    # Prefixes are stored dense (exact); the cache copy is
                    # where quantization happens.
                    self.cache = _install_row_quant(
                        self.cache, {"k": pf["k"], "v": pf["v"]},
                        jnp.int32(i),
                    )
                else:
                    self.cache = _admit_prefix_only(
                        self.cache, pf["k"], pf["v"], jnp.int32(i)
                    )
                first = self._pick_first(req, pf["last_logits"], plen)
            else:
                bl = self._suffix_bucket(plen, n)
                padded = self._padded_prompt(req.prompt, bl)
                if self.kv_quant:
                    last_logits, scratch = _prefill_scratch_prefixed(
                        self._req_params(req), pf["k"], pf["v"],
                        jnp.asarray(padded), jnp.int32(n), self.cfg,
                        plen + bl,
                    )
                    self.cache = install(self.cache, scratch, jnp.int32(i))
                else:
                    self.cache, last_logits = _admit_prefixed(
                        self._req_params(req), self.cache, pf["k"], pf["v"],
                        jnp.asarray(padded), jnp.int32(i), jnp.int32(n),
                        self.cfg,
                    )
                first = self._pick_first(req, last_logits, plen + n)
            return first, plen + n
        bl = self._bucket_len(n)
        padded = self._padded_prompt(req.prompt, bl)
        if (self.prefill_chunk is not None and bl > self.prefill_chunk
                and bl % self.prefill_chunk == 0):
            last_logits, scratch = _chunked_scratch_prefill(
                self._req_params(req), jnp.asarray(padded), jnp.int32(n),
                self.cfg, self.prefill_chunk,
            )
            self.cache = install(self.cache, scratch, jnp.int32(i))
        elif self.kv_quant:
            last_logits, scratch = _prefill_scratch(
                self._req_params(req), jnp.asarray(padded), jnp.int32(n),
                self.cfg, bl,
            )
            self.cache = install(self.cache, scratch, jnp.int32(i))
        else:
            self.cache, last_logits = _admit(
                self._req_params(req), self.cache, jnp.asarray(padded),
                jnp.int32(i), jnp.int32(n), self.cfg,
            )
        return self._pick_first(req, last_logits, n), n

    def _record_result(self, req: Request) -> None:
        """THE one place a finished/cancelled request's channels land."""
        self._results[req.rid] = np.asarray(req.generated, np.int32)
        if req.want_logprobs:
            self._logprob_results[req.rid] = np.asarray(
                req.logprobs, np.float32
            )

    def _on_retire(self, i: int) -> None:
        """Hook: slot i's request just finished (paged engine frees its
        blocks here)."""

    def _admit_waiting(self) -> list:
        """Admit queued requests into free slots. Returns the admission-time
        streaming deliveries [(callback, [token]), ...] for step() to fire
        AFTER all bookkeeping — a raising sink must never abort remaining
        admissions or the burst (the two-phase guarantee submit promises)."""
        fired: list = []
        for i in range(self.n_slots):
            if self._slot_req[i] is not None:
                continue
            # A request whose whole budget is the prefill token (or that
            # emits eos immediately) finishes during admission and never
            # occupies the slot — keep feeding the slot from the queue.
            while self._queue:
                req = self._queue.popleft()
                placed = self._install(req, i)
                if placed is None:
                    self._queue.appendleft(req)
                    return fired
                first, prompt_end = placed
                req.generated.append(first)
                done = req.max_new_tokens <= 1 or (
                    self.eos_id is not None and first == self.eos_id
                )
                if done:
                    self._record_result(req)
                    # The slot was never occupied, but _install may have
                    # claimed per-slot resources (the paged engine's block
                    # reservation) — release them.
                    self._on_retire(i)
                    if req.on_token is not None:
                        fired.append((req.on_token, [first]))
                    continue
                self._slot_req[i] = req
                self._slot_adapter[i] = self._adapter_idx[req.adapter]
                self.pos = self.pos.at[i].set(prompt_end)
                self.temp = self.temp.at[i].set(req.temperature)
                self.top_p = self.top_p.at[i].set(req.top_p)
                self.presence = self.presence.at[i].set(
                    req.presence_penalty
                )
                self.frequency = self.frequency.at[i].set(
                    req.frequency_penalty
                )
                if req.presence_penalty or req.frequency_penalty:
                    # Generated-only histogram (OpenAI semantics): starts
                    # at zero, counting just the admission token — prompt
                    # and prefix text never feed the penalties.
                    hist = np.zeros((self.cfg.vocab_size,), np.int32)
                    hist[first] = 1
                    if self.counts is None:  # lazy: [n_slots, vocab] i32
                        self.counts = jnp.zeros(
                            (self.n_slots, self.cfg.vocab_size), jnp.int32
                        )
                    self.counts = self.counts.at[i].set(jnp.asarray(hist))
                self.keys = self.keys.at[i].set(
                    jnp.asarray(self._req_key(req), jnp.uint32)
                )
                self.last_tok = self.last_tok.at[i].set(first)
                self.remaining = self.remaining.at[i].set(
                    req.max_new_tokens - 1
                )
                self.active = self.active.at[i].set(True)
                # Deliveries are deferred to step(): by fire time every
                # token is recorded and all slot/block bookkeeping (this
                # admission AND later ones) is consistent.
                if req.on_token is not None:
                    fired.append((req.on_token, [first]))
                break
        return fired

    def step(self):
        """One scheduler iteration: retire, admit, one fused decode burst."""
        self._retire()
        fired = self._admit_waiting()
        if not bool(np.asarray(self.active).any()):
            self._deliver(fired)
            return
        want_lp = any(
            r is not None and r.want_logprobs for r in self._slot_req
        )
        want_tp = any(
            r is not None and r.top_p < 1.0 and r.temperature > 0
            for r in self._slot_req
        )
        want_pen = any(
            r is not None and (r.presence_penalty or r.frequency_penalty)
            for r in self._slot_req
        )
        toks, emitted, lps = self._run_burst(want_lp, want_tp, want_pen)
        toks = np.asarray(toks)
        emitted = np.asarray(emitted)
        if want_lp:
            lps = np.asarray(lps)
        # Two phases: record EVERY slot's tokens, then fire callbacks
        # (admission-time deliveries included) — a raising callback must
        # never cost another request (or a later chunk of its own request)
        # its recorded tokens or a sibling sink its delivery.
        for i in range(self.n_slots):
            req = self._slot_req[i]
            if req is None:
                continue
            new = toks[emitted[:, i], i].tolist()
            req.generated.extend(new)
            if req.want_logprobs:
                req.logprobs.extend(lps[emitted[:, i], i].tolist())
            if req.on_token is not None and new:
                fired.append((req.on_token, new))
        self._deliver(fired)

    @staticmethod
    def _deliver(fired: list) -> None:
        """Fire streaming sinks; every sink gets its delivery before the
        first exception (if any) propagates."""
        first_exc = None
        for cb, new in fired:
            try:
                cb(new)
            except Exception as e:  # noqa: BLE001 — deliver to all sinks
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def _run_burst(self, with_logprobs: bool = False,
                   with_top_p: bool = False,
                   with_penalties: bool = False):
        (self.cache, self.pos, self.last_tok, self.remaining, self.active,
         toks, emitted, lps, counts) = _decode_burst(
            self._params_for(self._slot_adapter), self.cache, self.pos,
            self.last_tok,
            self.remaining, self.active, self.temp, self.keys, self.top_p,
            self.presence, self.frequency,
            self.counts if self.counts is not None else self._counts_dummy,
            self.cfg, self.steps_per_sync, self.eos_id, with_logprobs,
            with_top_p, with_penalties,
        )
        if counts is not None:
            self.counts = counts
        return toks, emitted, lps

    def take_logprobs(self, rid: int):
        """Pop the finished request's per-token model log-probs (aligned
        1:1 with its result tokens). None unless it was submitted with
        logprobs=True and has finished."""
        return self._logprob_results.pop(rid, None)

    def cancel(self, rid: int) -> bool:
        """Cancel a request: queued requests are dropped, active ones stop
        at the next sync boundary; either way the tokens generated so far
        become the request's result. Returns False when the rid is unknown
        or already finished (its result, if any, is untouched)."""
        for idx, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[idx]
                self._record_result(req)
                return True
        for i in range(self.n_slots):
            req = self._slot_req[i]
            if req is not None and req.rid == rid:
                # A slot can hold a request that already FINISHED in the
                # last burst but hasn't been swept yet — that's a
                # completion, not a cancellation.
                was_active = bool(np.asarray(self.active)[i])
                self.active = self.active.at[i].set(False)
                self._retire()  # one retirement path for all bookkeeping
                return was_active
        return False

    def stats(self) -> dict:
        """Scheduler snapshot: queue depth, slot occupancy, finished-but-
        uncollected results (the paged engine adds pool utilization)."""
        return {
            "queued": len(self._queue),
            "active_slots": int(np.asarray(self.active).sum()),
            "occupied_slots": sum(
                r is not None for r in self._slot_req
            ),
            "n_slots": self.n_slots,
            "results_pending": len(self._results),
        }

    def run(self) -> dict[int, np.ndarray]:
        """Drain the queue and all active slots; returns {rid: generated}."""
        while self._queue or any(r is not None for r in self._slot_req):
            self.step()
        self._retire()
        out, self._results = self._results, {}
        # Unclaimed logprobs from EARLIER drains would pile up forever in a
        # long-lived engine: keep only the batch being returned (poppable
        # via take_logprobs until the next run() returns).
        self._logprob_results = {
            r: v for r, v in self._logprob_results.items() if r in out
        }
        return out
