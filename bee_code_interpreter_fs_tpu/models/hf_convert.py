"""HuggingFace Llama/Mixtral checkpoint → this framework's param tree.

A user switching from any HF-format Llama-family checkpoint gets the exact
model here: `from_hf_state_dict(state_dict, cfg)` maps transformers' naming
(`model.layers.N.self_attn.q_proj.weight`, …) onto the stacked-layer tree
`init_params` produces, transposing projections to our [in, out] layout and
stacking layers along axis 0 (the lax.scan axis).

The one genuinely subtle step is RoPE: transformers stores q/k projection
rows in the ROTATE-HALF layout (the rotation pairs dimension i with
i + head_dim/2), while models/llama.py applies the INTERLEAVED convention
(pairs 2i / 2i+1 — the original GPT-J/Llama formulation). The two are
equivalent under a fixed permutation of each head's output rows, applied
here once at conversion time (`_unpermute_rope`), so runtime kernels stay
permutation-free. Correctness is pinned by tests/unit/test_hf_convert.py:
logits parity against transformers' own forward pass on randomly
initialized tiny models (dense, GQA, and Mixtral-MoE).

Tensors are accepted as anything numpy can view (torch CPU tensors
included); nothing here imports torch or transformers.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _np(t) -> np.ndarray:
    """View a checkpoint tensor (torch / numpy / array-like) as numpy.
    Published checkpoints ship bfloat16, which numpy cannot view — upcast
    those to float32 first (the tree is re-cast to the target dtype
    anyway)."""
    detach = getattr(t, "detach", None)
    if detach is not None:
        t = detach()
    if getattr(getattr(t, "dtype", None), "itemsize", None) == 2 and "bfloat16" in str(
        getattr(t, "dtype", "")
    ):
        t = t.float()
    return np.asarray(t)


def _unpermute_rope(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Reorder a [n_heads*hd, in] projection's output rows from HF's
    rotate-half layout to the interleaved layout _rope expects: per head,
    row 2i comes from i, row 2i+1 from i + hd/2."""
    out_dim, in_dim = w.shape
    hd = out_dim // n_heads
    half = hd // 2
    w = w.reshape(n_heads, hd, in_dim)
    interleaved = np.empty_like(w)
    interleaved[:, 0::2] = w[:, :half]
    interleaved[:, 1::2] = w[:, half:]
    return interleaved.reshape(out_dim, in_dim)


def from_hf_state_dict(state_dict, cfg, dtype=None):
    """Build this framework's param tree from a HF Llama/Mixtral state dict.

    Args:
      state_dict: mapping of HF parameter names to tensors (torch's
        `model.state_dict()`, a safetensors file's dict, …).
      cfg: the matching LlamaConfig (shapes are validated implicitly by the
        reshapes; set n_experts for Mixtral checkpoints).
      dtype: leaf dtype for the converted weights; default cfg.dtype.

    Returns the same tree structure as init_params(cfg) — drop-in for
    forward/prefill/generate/quantize_params.
    """
    dt = jnp.dtype(cfg.dtype if dtype is None else dtype)
    sd = {k: _np(v) for k, v in state_dict.items()}
    L = cfg.n_layers

    def take(fmt, i):
        return sd[fmt.format(i=i)]

    def stack(fmt, transform=lambda w: w):
        return jnp.asarray(
            np.stack([transform(take(fmt, i)) for i in range(L)]), dt
        )

    tl = "model.layers.{i}."
    layers = {
        "attn_norm": jnp.asarray(
            np.stack([take(tl + "input_layernorm.weight", i) for i in range(L)]),
            jnp.float32,
        ),
        "mlp_norm": jnp.asarray(
            np.stack(
                [take(tl + "post_attention_layernorm.weight", i) for i in range(L)]
            ),
            jnp.float32,
        ),
        # HF projections are [out, in]; ours are [in, out] → transpose.
        # q/k additionally unpermute to the interleaved RoPE layout.
        "wq": stack(
            tl + "self_attn.q_proj.weight",
            lambda w: _unpermute_rope(w, cfg.n_heads).T,
        ),
        "wk": stack(
            tl + "self_attn.k_proj.weight",
            lambda w: _unpermute_rope(w, cfg.n_kv_heads).T,
        ),
        "wv": stack(tl + "self_attn.v_proj.weight", lambda w: w.T),
        "wo": stack(tl + "self_attn.o_proj.weight", lambda w: w.T),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        moe = tl + "block_sparse_moe."

        def experts(wname):
            return jnp.asarray(
                np.stack(
                    [
                        np.stack(
                            [
                                sd[moe.format(i=i) + f"experts.{e}.{wname}.weight"].T
                                for e in range(E)
                            ]
                        )
                        for i in range(L)
                    ]
                ),
                dt,
            )

        layers.update(
            {
                "router": stack(moe + "gate.weight", lambda w: w.T),
                "w_gate": experts("w1"),
                "w_down": experts("w2"),
                "w_up": experts("w3"),
            }
        )
    else:
        layers.update(
            {
                "w_gate": stack(tl + "mlp.gate_proj.weight", lambda w: w.T),
                "w_up": stack(tl + "mlp.up_proj.weight", lambda w: w.T),
                "w_down": stack(tl + "mlp.down_proj.weight", lambda w: w.T),
            }
        )

    # Tied-embedding checkpoints (e.g. Llama-3.2-1B/3B) omit lm_head from
    # safetensors files (shared tensors aren't serialized) — the head IS
    # the embedding, transposed.
    lm_head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
    return {
        "embed": jnp.asarray(sd["model.embed_tokens.weight"], dt),
        "layers": layers,
        "final_norm": jnp.asarray(sd["model.norm.weight"], jnp.float32),
        "lm_head": jnp.asarray(lm_head.T, dt),
    }
