"""Weight-only int8 quantization for the Llama family (serving memory/
bandwidth optimization).

Autoregressive decoding is weight-HBM-bound: every step streams every matmul
weight once for a sliver of compute. Storing those weights as int8 with
per-output-channel scales halves the bytes per step versus bf16 — the
dequantize (`q.astype(dt) * s`) happens at the use site inside the layer
scan, so XLA reads 1 byte/param from HBM and fuses the convert+scale into
the matmul's operand path; the MXU still runs its native bf16 pipeline.

Scheme: symmetric per-output-channel. For a weight `w[*, in, out]` (the
contraction always runs over the second-to-last axis in this model family —
dense [in, out], stacked layers [L, in, out], stacked experts [L, E, in,
out]):

    s = max(|w|, axis=-2, keepdims) / 127        # one scale per out column
    q = clip(round(w / s), -127, 127).astype(int8)
    w ≈ q * s    (|error| <= s/2 per element)

Quantized: the seven per-layer matmul weights + lm_head. Left full
precision: embeddings (a gather, not a matmul — and tied-scale semantics
differ), norms (tiny, precision-critical), the MoE router (tiny, feeds a
softmax whose top-k is decision-critical).

The quantized tree is an ordinary pytree (each weight becomes
{"q": int8, "s": float32}), so it checkpoints through utils/checkpoint.py
and scans through lax.scan unchanged. `forward`/`prefill`/`decode_chunk`
accept it transparently via the `_w` accessor in models/llama.py.
"""

from __future__ import annotations

import jax.numpy as jnp

# Per-layer matmul weights that quantize (models/llama.py param tree).
QUANTIZED_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _sym_int8(x, axis: int):
    """The ONE symmetric-int8 recipe (f32 scale math — in bf16 the division
    near q=±127 can land a full level off and the scale itself carries
    ~0.4% rounding, breaking the |error| <= s/2 bound). Returns (q, s)."""
    x32 = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(x32), axis=axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)  # all-zero vectors must not divide by zero
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return q, s


def quantize_int8(w) -> dict:
    """Symmetric per-output-channel int8: w ≈ q * s (see module
    docstring); axis=-2 is the contraction dim of every matmul weight."""
    q, s = _sym_int8(w, axis=-2)
    return {"q": q, "s": s}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def dequantize(leaf, dtype):
    return leaf["q"].astype(dtype) * leaf["s"].astype(dtype)


def quantize_params(params) -> dict:
    """int8-quantize a Llama param tree's matmul weights (weight-only).

    Returns a new tree of the same structure with each quantized weight
    replaced by {"q": int8, "s": float32}; everything else is shared by
    reference. Works for dense and MoE trees (stacked expert weights
    quantize per (expert, out-channel) — axis=-2 is the contraction dim in
    every case).
    """
    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = quantize_int8(layers[name])
    out = dict(params)
    out["layers"] = layers
    out["lm_head"] = quantize_int8(params["lm_head"])
    return out


def quantized_param_specs(cfg) -> dict:
    """PartitionSpec tree matching quantize_params' structure, derived from
    the model's param_specs: each quantized weight's spec becomes
    {"q": <same spec>, "s": <spec with the contraction (-2) axis
    unsharded>} — the scale's -2 dim is size 1, so a mesh axis there would
    be meaningless. This is what keeps int8 serving compatible with the
    tp/ep distribution story (shard_pytree / sharded checkpoint restore)."""
    from jax.sharding import PartitionSpec as P

    from bee_code_interpreter_fs_tpu.models.llama import param_specs

    def qspec(spec):
        parts = list(spec)
        scale_parts = list(spec)
        scale_parts[-2] = None
        return {"q": P(*parts), "s": P(*scale_parts)}

    specs = param_specs(cfg)
    layers = dict(specs["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = qspec(layers[name])
    out = dict(specs)
    out["layers"] = layers
    out["lm_head"] = qspec(specs["lm_head"])
    return out


# ------------------------------------------------------------------ int4

def quantize_int4(w, group: int = 128) -> dict:
    """Symmetric GROUP-WISE int4: w ≈ unpack(q4) * s, two values per byte.

    int8's per-output-channel scale is too coarse at 4 bits (15 levels);
    scales here are per (group-of-`group`-inputs, output-channel), the
    standard weight-only-int4 recipe. Values clip to [-7, 7] (symmetric),
    and PACK explicitly — q4 stores two nibbles per int8 along the
    contraction axis, so the HBM bytes are genuinely 0.5/param on every
    backend (jnp.int4 arrays are byte-unpacked on some) plus the f32
    scales (1/group per weight column group).

    Shapes: w [*, in, out] → q4 [*, in/2, out] int8, s [*, in/group, 1,
    out] float32 (the singleton broadcasts over the group at dequant).
    `in` must divide by `group` (or by 2*ceil: group clamps to `in`).
    """
    w32 = w.astype(jnp.float32)
    *lead, n_in, n_out = w32.shape
    group = min(group, n_in)
    if n_in % group or group % 2:
        raise ValueError(f"in dim {n_in} must divide by even group {group}")
    g = w32.reshape(*lead, n_in // group, group, n_out)
    s = jnp.max(jnp.abs(g), axis=-2, keepdims=True) / 7.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(g / s), -7, 7).astype(jnp.int8)
    q = q.reshape(*lead, n_in, n_out)
    # Pack adjacent IN-axis pairs: even rows → low nibble, odd → high.
    lo = q[..., 0::2, :] & 0x0F
    hi = q[..., 1::2, :] & 0x0F
    packed = (lo | (hi << 4)).astype(jnp.int8)
    return {"q4": packed, "s4": s}


def is_quantized4(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q4", "s4"}


def dequantize4(leaf, dtype):
    packed, s = leaf["q4"], leaf["s4"]
    *lead, half_in, n_out = packed.shape
    n_in = 2 * half_in
    group = n_in // s.shape[-3]  # static: recovered from the scale shape
    # Sign-extend each nibble: shift up into the sign position, then
    # arithmetic-shift back down (int8 >> sign-extends).
    lo = (packed << 4).astype(jnp.int8) >> 4
    hi = packed >> 4
    # stack axis=-2 puts (lo_i, hi_i) adjacent; the reshape interleaves
    # them back to original row order 2i, 2i+1.
    q = jnp.stack([lo, hi], axis=-2).reshape(*lead, n_in, n_out)
    g = q.reshape(*lead, n_in // group, group, n_out)
    return (g.astype(jnp.float32) * s).reshape(*lead, n_in, n_out).astype(dtype)


def quantize4_params(params, group: int = 128) -> dict:
    """int4-quantize a Llama tree's matmul weights (same weight set as
    int8's quantize_params): ~0.25 bytes/param + scales — a 7B fits in
    ~3.6 GB, a 13B-class model on one v5e chip."""
    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = quantize_int4(layers[name], group)
    out = dict(params)
    out["layers"] = layers
    out["lm_head"] = quantize_int4(params["lm_head"], group)
    return out


def quantized4_param_specs(cfg) -> dict:
    """PartitionSpec tree matching quantize4_params' structure (the int4
    counterpart of quantized_param_specs): q4 keeps the weight's own spec
    (the packed in/2 axis shards under the same mesh axis as in), and s4
    — rank+1: [*, groups, 1, out] — shards only its OUT axis. The group
    axis stays replicated on purpose: group counts (in/group, e.g. 86 for
    a 7B w_down) routinely don't divide tp sizes the weight itself shards
    fine at, and the scales are ~1/group of the weight bytes — replicating
    them costs nothing."""
    from jax.sharding import PartitionSpec as P

    from bee_code_interpreter_fs_tpu.models.llama import param_specs

    def qspec(spec):
        parts = list(spec)
        scale_parts = parts[:-2] + [None, None, parts[-1]]
        return {"q4": P(*parts), "s4": P(*scale_parts)}

    specs = param_specs(cfg)
    layers = dict(specs["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = qspec(layers[name])
    out = dict(specs)
    out["layers"] = layers
    out["lm_head"] = qspec(specs["lm_head"])
    return out


def quantized_nbytes(params) -> int:
    """Total bytes of the weight leaves (quantized dicts count q + s) —
    the HBM-residency number the scheme exists to halve."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: is_quantized(x) or is_quantized4(x)
    ):
        if is_quantized(leaf):
            total += leaf["q"].nbytes + leaf["s"].nbytes
        elif is_quantized4(leaf):
            total += leaf["q4"].nbytes + leaf["s4"].nbytes
        else:
            total += leaf.nbytes
    return total


def random_quantized_params(key, cfg, precision: str = "int8") -> dict:
    """Random weight tree at cfg's exact shapes with the matmul weights
    ALREADY quantized — the bf16 tree never exists, so peak HBM stays at
    the quantized footprint (a 7B bf16 tree is ~13.5 GB and cannot
    coexist with its own quantized copy on a 16 GB v5e). Scales are sized
    like a real symmetric-quantized Gaussian init so logit magnitudes stay
    sane; the code path downstream (`_w` accessor, fused decode) is
    byte-for-byte the one real checkpoints take. Used by the true-scale
    single-chip benchmarks (examples/benchmark-7b.py,
    examples/benchmark-serving-7b.py)."""
    import jax
    import jax.numpy as jnp

    from bee_code_interpreter_fs_tpu.models.llama import init_params

    if precision not in ("int8", "int4"):
        raise ValueError(f"precision must be int8 or int4, got {precision!r}")
    shapes = jax.eval_shape(lambda k: init_params(k, cfg), key)

    def leaf(path_key, shape_dtype, k):
        shape = shape_dtype.shape
        if path_key in QUANTIZED_LAYER_WEIGHTS or path_key == "lm_head":
            kq, _ = jax.random.split(k)
            if precision == "int4":
                group = min(128, shape[-2])
                return {
                    # Random bytes = random nibble pairs; scale magnitude
                    # mirrors quantize_int4 of a 0.02-std init.
                    "q4": jax.random.randint(
                        kq, shape[:-2] + (shape[-2] // 2,) + shape[-1:],
                        -128, 128, jnp.int8,
                    ),
                    "s4": jnp.full(
                        shape[:-2] + (shape[-2] // group, 1) + shape[-1:],
                        shape[-2] ** -0.5 / 7.0,
                        jnp.float32,
                    ),
                }
            return {
                "q": jax.random.randint(kq, shape, -127, 128, jnp.int8),
                "s": jnp.full(
                    shape[:-2] + (1,) + shape[-1:],
                    shape[-2] ** -0.5 / 127.0,
                    jnp.float32,
                ),
            }
        if "norm" in path_key:
            return jnp.ones(shape, shape_dtype.dtype)
        return jax.random.normal(k, shape, jnp.float32).astype(
            shape_dtype.dtype
        ) * (0.02 if path_key != "embed" else 1.0)

    out = {}
    keyit = iter(jax.random.split(key, 64))
    for name, sub in shapes.items():
        if isinstance(sub, dict):
            out[name] = {
                child: leaf(child, sd, next(keyit))
                for child, sd in sub.items()
            }
        else:
            out[name] = leaf(name, sub, next(keyit))
    return out


# ---------------------------------------------------------- KV-cache int8

def quantize_kv(x):
    """Symmetric per-vector int8 for K/V cache entries: one f32 scale per
    trailing head_dim vector (the granularity a decode write produces).
    Halves the KV cache's HBM residency and read traffic — the decode-step
    bandwidth term that GROWS with context length, complementing
    weight-only quantization's fixed term. Returns (q int8 [...], s f32
    [..., 1])."""
    return _sym_int8(x, axis=-1)


def dequantize_kv(q, s, dtype):
    """`q * s` in the compute dtype (mirrors `dequantize`) — call at the
    attention read site so XLA fuses the dequantize into the contraction
    operand path and HBM serves 1 byte/element + scales."""
    return q.astype(dtype) * s.astype(dtype)
