"""Weight-only int8 quantization for the Llama family (serving memory/
bandwidth optimization).

Autoregressive decoding is weight-HBM-bound: every step streams every matmul
weight once for a sliver of compute. Storing those weights as int8 with
per-output-channel scales halves the bytes per step versus bf16 — the
dequantize (`q.astype(dt) * s`) happens at the use site inside the layer
scan, so XLA reads 1 byte/param from HBM and fuses the convert+scale into
the matmul's operand path; the MXU still runs its native bf16 pipeline.

Scheme: symmetric per-output-channel. For a weight `w[*, in, out]` (the
contraction always runs over the second-to-last axis in this model family —
dense [in, out], stacked layers [L, in, out], stacked experts [L, E, in,
out]):

    s = max(|w|, axis=-2, keepdims) / 127        # one scale per out column
    q = clip(round(w / s), -127, 127).astype(int8)
    w ≈ q * s    (|error| <= s/2 per element)

Quantized: the seven per-layer matmul weights + lm_head. Left full
precision: embeddings (a gather, not a matmul — and tied-scale semantics
differ), norms (tiny, precision-critical), the MoE router (tiny, feeds a
softmax whose top-k is decision-critical).

The quantized tree is an ordinary pytree (each weight becomes
{"q": int8, "s": float32}), so it checkpoints through utils/checkpoint.py
and scans through lax.scan unchanged. `forward`/`prefill`/`decode_chunk`
accept it transparently via the `_w` accessor in models/llama.py.
"""

from __future__ import annotations

import jax.numpy as jnp

# Per-layer matmul weights that quantize (models/llama.py param tree).
QUANTIZED_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_int8(w) -> dict:
    """Symmetric per-output-channel int8: w ≈ q * s (see module docstring).

    The scale/divide/round math runs in float32 regardless of the weight's
    dtype: in bf16 (the model default) the division near q=±127 can land a
    full level off and the scale itself carries ~0.4% rounding, breaking
    the |error| <= s/2 bound the scheme promises."""
    w32 = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w32), axis=-2, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)  # all-zero channels must not divide by zero
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def dequantize(leaf, dtype):
    return leaf["q"].astype(dtype) * leaf["s"].astype(dtype)


def quantize_params(params) -> dict:
    """int8-quantize a Llama param tree's matmul weights (weight-only).

    Returns a new tree of the same structure with each quantized weight
    replaced by {"q": int8, "s": float32}; everything else is shared by
    reference. Works for dense and MoE trees (stacked expert weights
    quantize per (expert, out-channel) — axis=-2 is the contraction dim in
    every case).
    """
    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = quantize_int8(layers[name])
    out = dict(params)
    out["layers"] = layers
    out["lm_head"] = quantize_int8(params["lm_head"])
    return out


def quantized_param_specs(cfg) -> dict:
    """PartitionSpec tree matching quantize_params' structure, derived from
    the model's param_specs: each quantized weight's spec becomes
    {"q": <same spec>, "s": <spec with the contraction (-2) axis
    unsharded>} — the scale's -2 dim is size 1, so a mesh axis there would
    be meaningless. This is what keeps int8 serving compatible with the
    tp/ep distribution story (shard_pytree / sharded checkpoint restore)."""
    from jax.sharding import PartitionSpec as P

    from bee_code_interpreter_fs_tpu.models.llama import param_specs

    def qspec(spec):
        parts = list(spec)
        scale_parts = list(spec)
        scale_parts[-2] = None
        return {"q": P(*parts), "s": P(*scale_parts)}

    specs = param_specs(cfg)
    layers = dict(specs["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        if name in layers:
            layers[name] = qspec(layers[name])
    out = dict(specs)
    out["layers"] = layers
    out["lm_head"] = qspec(specs["lm_head"])
    return out


def quantized_nbytes(params) -> int:
    """Total bytes of the weight leaves (quantized dicts count q + s) —
    the HBM-residency number the scheme exists to halve."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: is_quantized(x)
    ):
        if is_quantized(leaf):
            total += leaf["q"].nbytes + leaf["s"].nbytes
        else:
            total += leaf.nbytes
    return total
