"""gRPC server shell: service registration, TLS, health service.

Parity with the reference (src/code_interpreter/services/grpc_server.py:28-71)
— grpc.aio server, insecure or TLS port from config — plus the health service
the reference left as a TODO (grpc_server.py:71). grpcio's codegen plugin and
the reflection/health add-on packages are unavailable in this environment, so
services are registered via generic handlers against the vendored protos
(proto/*.proto), which needs no generated service stubs.
"""

from __future__ import annotations

import logging

import grpc

from ..config import Config
from ..proto import HEALTH_SERVICE_NAME, SERVICE_NAME, health_pb2
from .code_executor import CodeExecutor
from .custom_tool_executor import CustomToolExecutor
from .grpc_servicers.code_interpreter_servicer import CodeInterpreterServicer
from .storage import Storage

logger = logging.getLogger(__name__)


class HealthServicer:
    """grpc.health.v1.Health — Check + Watch (single-update stream)."""

    def __init__(self) -> None:
        self.serving = True

    async def Check(self, request, context) -> health_pb2.HealthCheckResponse:
        if request.service not in ("", SERVICE_NAME, HEALTH_SERVICE_NAME):
            await context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
        status = (
            health_pb2.HealthCheckResponse.SERVING
            if self.serving
            else health_pb2.HealthCheckResponse.NOT_SERVING
        )
        return health_pb2.HealthCheckResponse(status=status)

    async def Watch(self, request, context):
        yield await self.Check(request, context)

    def method_handlers(self) -> dict[str, grpc.RpcMethodHandler]:
        return {
            "Check": grpc.unary_unary_rpc_method_handler(
                self.Check,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
            "Watch": grpc.unary_stream_rpc_method_handler(
                self.Watch,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
        }


class GrpcServer:
    def __init__(
        self,
        config: Config,
        code_executor: CodeExecutor,
        custom_tool_executor: CustomToolExecutor,
        storage: Storage,
    ) -> None:
        self.config = config
        self.servicer = CodeInterpreterServicer(code_executor, custom_tool_executor)
        self.health = HealthServicer()
        self.server = grpc.aio.server()
        self.server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    SERVICE_NAME, self.servicer.method_handlers()
                ),
                grpc.method_handlers_generic_handler(
                    HEALTH_SERVICE_NAME, self.health.method_handlers()
                ),
            )
        )
        self.port: int | None = None

    def _credentials(self) -> grpc.ServerCredentials | None:
        cfg = self.config
        if cfg.grpc_tls_cert and cfg.grpc_tls_cert_key:
            return grpc.ssl_server_credentials(
                [(cfg.grpc_tls_cert_key, cfg.grpc_tls_cert)],
                root_certificates=cfg.grpc_tls_ca_cert,
                require_client_auth=bool(cfg.grpc_tls_ca_cert),
            )
        return None

    async def start(self) -> int:
        addr = self.config.grpc_listen_addr
        creds = self._credentials()
        if creds is not None:
            self.port = self.server.add_secure_port(addr, creds)
        else:
            self.port = self.server.add_insecure_port(addr)
        if self.port == 0:
            raise RuntimeError(f"failed to bind gRPC port at {addr}")
        await self.server.start()
        logger.info(
            "gRPC listening on %s (tls=%s)", addr, "on" if creds else "off"
        )
        return self.port

    async def wait_for_termination(self) -> None:
        await self.server.wait_for_termination()

    async def stop(self, grace: float = 5.0) -> None:
        await self.server.stop(grace)
