"""gRPC server shell: service registration, TLS, health service.

Parity with the reference (src/code_interpreter/services/grpc_server.py:28-71)
— grpc.aio server, insecure or TLS port from config — plus the health service
the reference left as a TODO (grpc_server.py:71). grpcio's codegen plugin and
the reflection/health add-on packages are unavailable in this environment, so
services are registered via generic handlers against the vendored protos
(proto/*.proto), which needs no generated service stubs.
"""

from __future__ import annotations

import logging
import re
from collections.abc import Callable

import grpc
from google.protobuf import descriptor_pool

from ..config import Config
from ..proto import (
    HEALTH_SERVICE_NAME,
    REFLECTION_SERVICE_NAME,
    SERVICE_NAME,
    health_pb2,
    reflection_pb2,
)
from .code_executor import CodeExecutor
from .custom_tool_executor import CustomToolExecutor
from .grpc_servicers.code_interpreter_servicer import CodeInterpreterServicer
from .storage import Storage

logger = logging.getLogger(__name__)


class HealthServicer:
    """grpc.health.v1.Health — Check + Watch (single-update stream).

    ``degraded_check`` (graceful degradation) is consulted at Check time: a
    control plane whose default-lane spawn breaker is open reports
    NOT_SERVING so load balancers drain it while it cannot take new work —
    health that reflects reality, not process liveness. It recovers on the
    breaker's half-open probe success without a restart.

    Per-lane degradation is reported through health service NAMES: checking
    service ``lane-<n>`` (bare, or suffixed onto the main service as
    ``<SERVICE_NAME>/lane-<n>``) answers for chip-count lane n alone via
    ``lane_degraded_check`` — a dead 4-chip nodepool reads NOT_SERVING on
    ``lane-4`` while ``lane-0`` CPU traffic stays SERVING, so a per-lane
    load balancer can drain exactly the broken slice shape."""

    LANE_SERVICE_RE = re.compile(
        rf"^(?:{re.escape(SERVICE_NAME)}/)?lane-(\d+)$"
    )

    def __init__(
        self,
        degraded_check: Callable[[], bool] | None = None,
        lane_degraded_check: Callable[[int], bool] | None = None,
    ) -> None:
        self.serving = True
        self.degraded_check = degraded_check
        self.lane_degraded_check = lane_degraded_check

    def _currently_serving(self, lane: int | None = None) -> bool:
        if not self.serving:
            return False
        if lane is not None:
            if self.lane_degraded_check is not None:
                return not self.lane_degraded_check(lane)
            return True
        if self.degraded_check is not None and self.degraded_check():
            return False
        return True

    async def Check(self, request, context) -> health_pb2.HealthCheckResponse:
        lane: int | None = None
        lane_match = self.LANE_SERVICE_RE.match(request.service)
        if lane_match is not None:
            lane = int(lane_match.group(1))
        elif request.service not in ("", SERVICE_NAME, HEALTH_SERVICE_NAME):
            await context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
        status = (
            health_pb2.HealthCheckResponse.SERVING
            if self._currently_serving(lane)
            else health_pb2.HealthCheckResponse.NOT_SERVING
        )
        return health_pb2.HealthCheckResponse(status=status)

    async def Watch(self, request, context):
        yield await self.Check(request, context)

    def method_handlers(self) -> dict[str, grpc.RpcMethodHandler]:
        return {
            "Check": grpc.unary_unary_rpc_method_handler(
                self.Check,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
            "Watch": grpc.unary_stream_rpc_method_handler(
                self.Watch,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
        }


class ReflectionServicer:
    """grpc.reflection.v1alpha.ServerReflection, served first-party.

    The reference enables reflection via the grpcio add-on package
    (src/code_interpreter/services/grpc_server.py:67-69) and its README
    workflow depends on it (grpcurl, README.md:46). That package is not
    available here, so the protocol is implemented directly over the default
    descriptor pool the vendored *_pb2 modules register into — same approach
    as the hand-rolled health service above.
    """

    def __init__(self, service_names: list[str]) -> None:
        self.service_names = sorted(service_names)
        self.pool = descriptor_pool.Default()

    # -- descriptor closure ------------------------------------------------

    def _file_closure(self, fd) -> list[bytes]:
        """The file plus its transitive imports, each as a serialized
        FileDescriptorProto (grpcurl needs the full closure to decode)."""
        seen: dict[str, bytes] = {}

        def visit(file_descriptor) -> None:
            if file_descriptor.name in seen:
                return
            seen[file_descriptor.name] = file_descriptor.serialized_pb
            for dep in file_descriptor.dependencies:
                visit(dep)

        visit(fd)
        return list(seen.values())

    def _respond(
        self, request: reflection_pb2.ServerReflectionRequest
    ) -> reflection_pb2.ServerReflectionResponse:
        response = reflection_pb2.ServerReflectionResponse(
            valid_host=request.host, original_request=request
        )
        kind = request.WhichOneof("message_request")
        try:
            if kind == "list_services":
                response.list_services_response.service.extend(
                    reflection_pb2.ServiceResponse(name=name)
                    for name in self.service_names
                )
            elif kind == "file_containing_symbol":
                fd = self.pool.FindFileContainingSymbol(
                    request.file_containing_symbol
                )
                response.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_closure(fd)
                )
            elif kind == "file_by_filename":
                fd = self.pool.FindFileByName(request.file_by_filename)
                response.file_descriptor_response.file_descriptor_proto.extend(
                    self._file_closure(fd)
                )
            elif kind == "all_extension_numbers_of_type":
                # proto3 services here define no extensions; report the type
                # with an empty number list if it exists at all.
                self.pool.FindMessageTypeByName(
                    request.all_extension_numbers_of_type
                )
                response.all_extension_numbers_response.base_type_name = (
                    request.all_extension_numbers_of_type
                )
            elif kind == "file_containing_extension":
                raise KeyError("extensions are not used by this server")
            else:
                response.error_response.error_code = int(
                    grpc.StatusCode.INVALID_ARGUMENT.value[0]
                )
                response.error_response.error_message = "empty message_request"
        except KeyError as e:
            response.error_response.error_code = int(
                grpc.StatusCode.NOT_FOUND.value[0]
            )
            response.error_response.error_message = str(e)
        return response

    async def ServerReflectionInfo(self, request_iterator, context):
        async for request in request_iterator:
            yield self._respond(request)

    def method_handlers(self) -> dict[str, grpc.RpcMethodHandler]:
        return {
            "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                self.ServerReflectionInfo,
                request_deserializer=(
                    reflection_pb2.ServerReflectionRequest.FromString
                ),
                response_serializer=(
                    reflection_pb2.ServerReflectionResponse.SerializeToString
                ),
            ),
        }


class GrpcServer:
    def __init__(
        self,
        config: Config,
        code_executor: CodeExecutor,
        custom_tool_executor: CustomToolExecutor,
        storage: Storage,
    ) -> None:
        self.config = config
        self.servicer = CodeInterpreterServicer(code_executor, custom_tool_executor)
        self.health = HealthServicer(
            degraded_check=code_executor.degraded,
            lane_degraded_check=code_executor.lane_degraded,
        )
        self.reflection = ReflectionServicer(
            [SERVICE_NAME, HEALTH_SERVICE_NAME, REFLECTION_SERVICE_NAME]
        )
        self.server = grpc.aio.server()
        self.server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    SERVICE_NAME, self.servicer.method_handlers()
                ),
                grpc.method_handlers_generic_handler(
                    HEALTH_SERVICE_NAME, self.health.method_handlers()
                ),
                grpc.method_handlers_generic_handler(
                    REFLECTION_SERVICE_NAME, self.reflection.method_handlers()
                ),
            )
        )
        self.port: int | None = None

    def _credentials(self) -> grpc.ServerCredentials | None:
        cfg = self.config
        if cfg.grpc_tls_cert and cfg.grpc_tls_cert_key:
            return grpc.ssl_server_credentials(
                [(cfg.grpc_tls_cert_key, cfg.grpc_tls_cert)],
                root_certificates=cfg.grpc_tls_ca_cert,
                require_client_auth=bool(cfg.grpc_tls_ca_cert),
            )
        return None

    async def start(self) -> int:
        addr = self.config.grpc_listen_addr
        creds = self._credentials()
        if creds is not None:
            self.port = self.server.add_secure_port(addr, creds)
        else:
            self.port = self.server.add_insecure_port(addr)
        if self.port == 0:
            raise RuntimeError(f"failed to bind gRPC port at {addr}")
        await self.server.start()
        logger.info(
            "gRPC listening on %s (tls=%s)", addr, "on" if creds else "off"
        )
        return self.port

    async def wait_for_termination(self) -> None:
        await self.server.wait_for_termination()

    async def stop(self, grace: float = 5.0) -> None:
        await self.server.stop(grace)
