"""HTTP API (aiohttp): Execute, custom tools, and the files CRUD.

Endpoint parity with the reference's FastAPI app
(src/code_interpreter/services/http_server.py:75-215): POST /v1/execute,
POST /v1/parse-custom-tool, POST /v1/execute-custom-tool, PUT /v1/files,
GET/DELETE /v1/files/{hash}. Differences by design:

- /v1/execute accepts BOTH inline `source_code` and `source_file` (the
  reference required source_file while its own tests posted source_code —
  SURVEY.md §0.1); plus TPU fields `chip_count` and `env`.
- Responses include per-phase timings; GET /healthz is a cheap liveness probe.
- FastAPI/uvicorn are not available in this environment; aiohttp serves the
  same surface.
"""

from __future__ import annotations

import json
import logging
import math
import os

from aiohttp import web
from pydantic import BaseModel, Field, ValidationError

from ..utils import tracing
from ..utils.logs import new_request_id, request_id_var
from ..utils.metrics import PROMETHEUS_CONTENT_TYPE
from ..utils.tracing import TRACE_ID_RE, Tracer
from ..utils.validation import OBJECT_ID_RE
from .backends.base import SandboxSpawnError
from .code_executor import (
    CircuitOpenError,
    CodeExecutor,
    ExecutorError,
    LimitExceededError,
    QuotaExceededError,
    SessionLimitError,
    SessionRestoringError,
    StaleLeaseError,
    StateStoreDegradedError,
)
from .custom_tool_executor import (
    CustomToolExecuteError,
    CustomToolExecutor,
    CustomToolParseError,
)
from .perf_observer import summarize_profile
from .storage import Storage, StorageObjectNotFound

logger = logging.getLogger(__name__)


class ExecuteRequest(BaseModel):
    source_code: str | None = None
    source_file: str | None = None
    files: dict[str, str] = Field(default_factory=dict)
    timeout: float | None = Field(default=None, gt=0)
    env: dict[str, str] | None = None
    chip_count: int | None = Field(default=None, ge=0)
    profile: bool = False
    # Session affinity: requests sharing an executor_id run in one live
    # sandbox whose workspace persists across them. Empty/absent = stateless.
    executor_id: str | None = None
    # Admission control (fair-share scheduler). Body fields win; the
    # X-Tenant / X-Priority / X-Deadline-Seconds headers are the fallback
    # (gateways that can't rewrite bodies set headers). Absent = shared
    # tenant, interactive class, no deadline.
    tenant: str | None = None
    priority: str | None = None  # "interactive" | "batch"
    # Start within N seconds; 0 = "only if a slot is free right now".
    # ge (not gt) to match the header/metadata paths, which the scheduler
    # validates with the same >= 0 rule — one value, one verdict.
    deadline: float | None = Field(default=None, ge=0)
    # Per-request resource budget override (keys from services.limits:
    # memory_bytes/cpu_seconds/nproc/nofile/fsize_bytes/disk_bytes/
    # output_bytes). Layers over the configured default + lane budgets and
    # is min-clamped by the server caps — only ever tightens. Header
    # fallback: X-Sandbox-Limits (a JSON object). Breaches return 422 with
    # the typed violation kind.
    limits: dict[str, float] | None = None
    # Purity declaration (result memoization): this run reads no network,
    # no randomness, no wall clock — its output is a function of its
    # inputs. Declared-pure runs ride the content-addressed result memo:
    # an identical earlier run answers from its record (X-Memo: hit) with
    # zero chip-seconds billed. A promise, not a sandbox restriction.
    pure: bool = False


class ParseCustomToolRequest(BaseModel):
    tool_source_code: str


class ExecuteCustomToolRequest(BaseModel):
    tool_source_code: str
    tool_input_json: str
    # Same session semantics as ExecuteRequest.executor_id: tool calls
    # sharing an id see each other's workspace files.
    executor_id: str | None = None
    timeout: float | None = Field(default=None, gt=0)
    # The session-affinity key's tenant half (body-then-X-Tenant-header,
    # the same resolution as /v1/execute): a session created with a body
    # tenant must hash to the SAME replica from every route that can
    # touch it. Routing-only on this surface — custom-tool admission
    # itself runs under the shared tenant, as before.
    tenant: str | None = None


def _usage_row_text(tenant: str, row: dict) -> str:
    """One tenant's ledger line for the text renderers (shared by
    /statusz?format=text and /usage?format=text)."""
    violations = row.get("violations") or {}
    violation_text = (
        " violations["
        + " ".join(f"{k}={int(v)}" for k, v in sorted(violations.items()))
        + "]"
        if violations
        else ""
    )
    return (
        f"  {tenant}: chip_s={row.get('chip_seconds', 0.0)} "
        f"queue_s={row.get('queue_wait_seconds', 0.0)} "
        f"requests={int(row.get('requests', 0))} "
        f"batch_jobs={int(row.get('batch_jobs', 0))} "
        f"up_bytes={int(row.get('upload_bytes', 0))} "
        f"down_bytes={int(row.get('download_bytes', 0))} "
        f"recompiles={int(row.get('compile_cache_recompiles', 0))}"
        + violation_text
    )


def usage_text(body: dict) -> str:
    """Human-readable GET /usage (`?format=text`)."""
    if not body.get("enabled", False):
        return "usage metering: disabled\n"
    lines = [
        f"usage metering: tenants={body.get('tenant_count', 0)}"
        f"/{body.get('max_tenants', 0)} "
        f"flushes={body.get('flushes', 0)} "
        f"journal_lines={body.get('journal_lines', 0)}",
    ]
    tenants = body.get("tenants", {})
    if tenants:
        for tenant, row in sorted(tenants.items()):
            lines.append(_usage_row_text(tenant, row))
    else:
        lines.append("  (no usage recorded)")
    return "\n".join(lines) + "\n"


def _quota_row_text(tenant: str, row: dict) -> str:
    """One tenant's quota line for the text renderers (shared by
    /statusz?format=text and /quotas?format=text)."""
    policy = row.get("policy", {})
    budget = policy.get("chip_seconds_per_window", 0)
    parts = [f"  {tenant}:"]
    if budget:
        parts.append(
            f"chip_s={row.get('used_chip_seconds_window', 0.0)}/{budget}"
        )
    else:
        parts.append(
            f"chip_s={row.get('used_chip_seconds_window', 0.0)} (no budget)"
        )
    parts.append(f"in_flight={row.get('in_flight', 0)}")
    parts.append(f"denials={row.get('denials', 0)}")
    quarantined = row.get("quarantined_for_s", 0.0)
    if quarantined:
        parts.append(
            f"QUARANTINED {quarantined}s"
            f" (level {row.get('offender_level', 0)})"
        )
    elif row.get("offender_level", 0):
        parts.append(f"offender_level={row.get('offender_level', 0)}")
    return " ".join(parts)


def quotas_text(body: dict) -> str:
    """Human-readable GET /quotas (`?format=text`)."""
    if not body.get("enabled", False):
        return "quota enforcement: disabled\n"
    default = body.get("default_policy", {})
    lines = [
        "quota enforcement: "
        f"denials={body.get('denials_total', 0)} "
        f"policy_file={body.get('policy_file') or '(none)'} "
        f"overrides={len(body.get('tenant_overrides', ()))}",
        "  default: "
        f"chip_s/window={default.get('chip_seconds_per_window', 0)} "
        f"window={default.get('window_seconds', 0)}s "
        f"req/window={default.get('requests_per_window', 0)} "
        f"concurrent={default.get('max_concurrent', 0)} "
        f"violations/window={default.get('violations_per_window', 0)}",
    ]
    tenants = body.get("tenants", {})
    if tenants:
        for tenant, row in sorted(tenants.items()):
            lines.append(_quota_row_text(tenant, row))
    else:
        lines.append("  (no tenants observed)")
    return "\n".join(lines) + "\n"


def perf_text(body: dict) -> str:
    """Human-readable GET /perf (`?format=text`)."""
    if not body.get("enabled", False):
        return "perf observer: disabled\n"
    lines = [_perf_header_text(body)]
    series = body.get("series", {})
    if series:
        for key, row in sorted(series.items()):
            lines.append(_perf_series_text(key, row))
    else:
        lines.append("  (no latency series yet)")
    tenants = body.get("tenants", {})
    for tenant, row in sorted(tenants.items()):
        lines.append(_perf_series_text(f"tenant {tenant}", row))
    store = body.get("profile_store")
    if store is not None:
        lines.append(
            f"profiles: {store.get('entries', 0)} entries "
            f"{store.get('bytes', 0)} bytes "
            f"(captured {body.get('auto_profile', {}).get('captured', 0)}, "
            f"evictions {store.get('evictions', 0)})"
        )
    return "\n".join(lines) + "\n"


def _perf_header_text(body: dict) -> str:
    bands = body.get("bands", {})
    return (
        f"perf observer: status={body.get('status', 'normal')} "
        f"window={body.get('window_seconds', 0)}s "
        f"drift_q=p{int(float(body.get('drift_quantile', 0.95)) * 100)} "
        f"bands=x{bands.get('degraded_factor', 0)}"
        f"/x{bands.get('regressed_factor', 0)}"
    )


def _perf_series_text(key: str, row: dict) -> str:
    """One latency series' line for the text renderers (shared by
    /statusz?format=text and /perf?format=text)."""
    marker = "!!" if row.get("state") == "regressed" else "  "
    baseline = row.get("baseline_s")
    return (
        f"{marker}{key}: [{row.get('state', 'normal')}] "
        f"p50={row.get('p50_s', 0.0)}s p95={row.get('p95_s', 0.0)}s "
        f"p99={row.get('p99_s', 0.0)}s "
        f"baseline={baseline if baseline is not None else '-'}s "
        f"n={row.get('count', 0)} windows={row.get('windows', 0)}"
        + (
            f" regressions={row.get('regressions', 0)}"
            if row.get("regressions")
            else ""
        )
    )


def statusz_text(body: dict) -> str:
    """Human-readable /statusz (`?format=text`): the at-a-glance view
    that replaces the ssh-and-grep loop onchip_watch.sh encoded.
    Module-level (not a handler closure) so the renderer is directly
    testable against edge-case bodies — empty fleet, overflow rows,
    wedged hosts with evidence."""
    lines = [
        f"status: {body.get('status', 'unknown')}   "
        f"inflight: {body.get('inflight', 0)}",
        "",
        "lanes:",
    ]
    for lane, entry in sorted(body.get("lanes", {}).items()):
        lines.append(
            f"  lane {lane}: pool={entry.get('pool_depth', 0)}"
            f"/{entry.get('pool_target', 0)} "
            f"in_use={entry.get('in_use', 0)} "
            f"sessions={entry.get('session_held', 0)} "
            f"spawning={entry.get('spawning', 0)} "
            f"queued={entry.get('queued', 0)} "
            f"wait_ewma={entry.get('queue_wait_ewma_s', 0.0)}s "
            f"batch_occ={entry.get('batch_occupancy', 0.0)} "
            f"breaker={entry.get('breaker', 'closed')}"
        )
    if not body.get("lanes"):
        lines.append("  (no lanes)")
    autoscaler = body.get("autoscaler", {})
    lines.append("")
    if autoscaler.get("enabled"):
        lines.append(
            f"autoscaler: bounds=[{autoscaler.get('min_target')}"
            f"..{autoscaler.get('max_target')}] "
            f"static={autoscaler.get('static_target')}"
        )
        for lane, row in sorted(autoscaler.get("lanes", {}).items()):
            lines.append(
                f"  lane {lane}: target={row.get('target')} "
                f"demand={row.get('raw_demand')} "
                f"rate={row.get('arrival_rate_per_s')}/s "
                f"ups={row.get('scale_ups')} downs={row.get('scale_downs')} "
                f"reaped={row.get('reaped')}"
            )
    else:
        lines.append(
            "autoscaler: disabled "
            f"(static target {autoscaler.get('static_target', '?')})"
        )
    health = body.get("device_health", {})
    lines.append("")
    if health.get("enabled"):
        states = health.get("states", {})
        lines.append(
            "device health: "
            + " ".join(f"{k}={v}" for k, v in states.items())
            + f"   last_poll_age={health.get('last_poll_age_s')}s"
        )
        for host in health.get("hosts", ()):
            marker = "!!" if host.get("state") == "wedged" else "  "
            lines.append(
                f"{marker}lane {host.get('lane')} {host.get('host')} "
                f"[{host.get('state')}]"
                + (f" {host['reason']}" if host.get("reason") else "")
                + (
                    f" stall={host['stall_s']}s"
                    if host.get("stall_s")
                    else ""
                )
            )
    else:
        lines.append("device health: probe disabled")
    recovery = body.get("recovery", {})
    if recovery.get("fencing_enabled"):
        budget = recovery.get("fence_budget", {})
        lines.append(
            f"recovery: fences={recovery.get('fences_total', 0)} "
            f"readmissions={recovery.get('readmissions_total', 0)} "
            f"budget={budget.get('max_per_window', 0)}"
            f"/{budget.get('window_seconds', 0)}s "
            f"streak={recovery.get('readmit_streak', 0)}"
        )
        for scope, row in sorted(recovery.get("recovering", {}).items()):
            lines.append(
                f"  recovering {scope}: {row.get('streak')}/"
                f"{row.get('need')} clean ({row.get('reason', '')}, "
                f"{row.get('for_s')}s, {row.get('relapses')} relapse(s))"
            )
    elif recovery:
        lines.append("recovery: fencing disabled")
    cc = body.get("compile_cache", {})
    lines.append(
        f"compile cache: enabled={cc.get('enabled')} "
        f"entries={cc.get('entries')} bytes={cc.get('bytes')}"
    )
    otlp = body.get("otlp", {})
    if otlp.get("enabled"):
        lines.append(
            f"otlp: {otlp.get('endpoint')} queued={otlp.get('queued_spans')} "
            f"exported={otlp.get('exported_spans')} "
            f"dropped={otlp.get('dropped_spans')} "
            f"failures={otlp.get('export_failures')}"
        )
    else:
        lines.append("otlp: disabled")
    replicas = body.get("replicas", {})
    if replicas.get("enabled"):
        live = replicas.get("live")
        lines.append(
            f"replicas: self={replicas.get('self')} "
            + (
                f"live={'/'.join(live)} "
                f"proxied={replicas.get('proxied_total', 0)} "
                f"redirected={replicas.get('redirected_total', 0)}"
                if live is not None
                else f"store={replicas.get('store', '?')} (no peer ring)"
            )
        )
    usage = body.get("usage", {})
    if usage.get("enabled"):
        lines.append(
            f"usage: tenants={usage.get('tenant_count', 0)}"
            f"/{usage.get('max_tenants', 0)} "
            f"flushes={usage.get('flushes', 0)}"
        )
        for tenant, row in sorted(usage.get("tenants", {}).items()):
            lines.append(_usage_row_text(tenant, row))
    else:
        lines.append("usage: metering disabled")
    quotas = body.get("quotas", {})
    if quotas.get("enabled"):
        lines.append(
            f"quotas: denials={quotas.get('denials_total', 0)} "
            f"overrides={len(quotas.get('tenant_overrides', ()))}"
        )
        for tenant, row in sorted(quotas.get("tenants", {}).items()):
            lines.append(_quota_row_text(tenant, row))
    else:
        lines.append("quotas: enforcement disabled")
    perf = body.get("perf", {})
    if perf.get("enabled"):
        lines.append(_perf_header_text(perf))
        for key, row in sorted(perf.get("series", {}).items()):
            lines.append(_perf_series_text(key, row))
        store = perf.get("profile_store")
        if store is not None and (
            store.get("entries") or perf.get("auto_profile", {}).get("captured")
        ):
            lines.append(
                f"profiles: {store.get('entries', 0)} entries "
                f"{store.get('bytes', 0)} bytes"
            )
    else:
        lines.append("perf observer: disabled")
    sessions = body.get("sessions", ())
    durability = body.get("session_durability", {})
    if durability.get("enabled"):
        lines.append(
            f"sessions: {len(sessions)} live, "
            f"{durability.get('hibernated', 0)} hibernated "
            f"(saves={durability.get('saves', 0)} "
            f"restores={durability.get('restores', 0)} "
            f"conflicts={durability.get('conflicts', 0)} "
            f"idle_chip_s={durability.get('idle_chip_seconds_total', 0.0)})"
        )
    else:
        lines.append(f"sessions: {len(sessions)}")
    for row in sessions:
        lines.append(
            f"  {row.get('executor_id')}: lane={row.get('chip_count')} "
            f"idle={row.get('idle_s')}s busy={row.get('busy')} "
            f"requests={row.get('requests')} [{row.get('status')}]"
        )
    return "\n".join(lines) + "\n"


def create_http_app(
    code_executor: CodeExecutor,
    custom_tool_executor: CustomToolExecutor,
    storage: Storage,
    tracer: Tracer | None = None,
    router=None,
) -> web.Application:
    tracer = tracer or code_executor.tracer
    # Session→replica affinity (services/replicas.py): with a replica set
    # configured, session requests this replica does not own are proxied
    # (or 307-redirected) to the owner. None = single-replica mode: zero
    # routing code on any path.
    router = router if router is not None else code_executor.session_router

    async def route_session(
        request: web.Request, tenant: str | None, executor_id: str | None
    ):
        """Affinity gate for session-carrying routes: None = serve locally
        (stateless request, we own the key, or single-replica mode); a
        Response = the owner's answer (transparent proxy) or the 307
        redirect contract. A dead owner drops off the ring inside
        `forward`, so the loop re-evaluates against the survivors — the
        failover path: the key rehashes (usually to us) and serving
        continues after lease-fenced turnover of the dead owner's hosts.
        NOTE: the proxied NDJSON stream is relayed buffered — incremental
        events coalesce; the final body is identical."""
        if router is None or not executor_id:
            return None
        if router.peer_forwarded(request.headers.get("X-Replica-Forwarded-By")):
            # Forwarded by a PEER (the header carries the fleet's
            # shared-store secret — a client-spoofed value fails the
            # check and routes normally): serve HERE regardless of what
            # this replica's ring says. Ring views can diverge for up to
            # one TTL (per-replica proxy suspicions), and without this
            # guard a disagreement becomes an unbounded A→B→C→A proxy
            # cycle — one hop of disagreement costs at most one misplaced
            # session, never a loop.
            return None
        for _ in range(1 + len(router.ring.peers)):
            owner = router.owner_of(tenant, executor_id)
            if owner == router.ring.self_id:
                return None
            response = await router.forward(request, owner)
            if response is not None:
                return response
        return None

    def session_tenant(request: web.Request, req=None) -> str | None:
        """The tenant half of the affinity key — the SAME body-then-header
        resolution the scheduler sees, so routing and admission can never
        hash a session to different tenants."""
        body_tenant = getattr(req, "tenant", None) if req is not None else None
        return body_tenant or request.headers.get("X-Tenant")

    @web.middleware
    async def request_context_middleware(request: web.Request, handler):
        """Per-request correlation: a fresh request id (logging ContextVar,
        echoed as X-Request-Id — before this PR the id existed only in
        logs), and for the business API a root trace span joined from the
        client's `traceparent` header. Probes/scrapes (/healthz, /metrics)
        and the trace-debug surface itself stay untraced."""
        rid = new_request_id()
        trace_ctx = None
        if request.path.startswith("/v1/"):
            # Span names must be a BOUNDED set (they label the span_seconds
            # histogram): use the route template ("/v1/files/{hash}"), never
            # the raw path — file hashes / executor ids / 404 garbage would
            # mint a metric series each. The raw path rides as a span
            # attribute instead (attributes never become metric labels).
            resource = request.match_info.route.resource
            canonical = resource.canonical if resource is not None else "unmatched"
            trace_ctx = tracer.start_trace(
                f"http {request.method} {canonical}",
                traceparent=request.headers.get("traceparent"),
                attributes={
                    "http.method": request.method,
                    "http.path": request.path,
                    "request_id": rid,
                },
            )

        def stamp(response) -> None:
            # A prepared response (the NDJSON stream) already sent its
            # headers; mutating them now would be a silent no-op at best.
            if getattr(response, "prepared", False):
                return
            response.headers["X-Request-Id"] = rid
            if trace_ctx is not None and trace_ctx.trace_id:
                response.headers["X-Trace-Id"] = trace_ctx.trace_id
                # Emit the context too (accept/emit symmetry): lets a
                # caller that did NOT send a traceparent adopt the trace
                # this service started for it.
                header = trace_ctx.traceparent()
                if header:
                    response.headers["traceparent"] = header

        if trace_ctx is None:
            response = await handler(request)
            stamp(response)
            return response
        with trace_ctx as span:
            try:
                response = await handler(request)
            except web.HTTPException as e:
                stamp(e)
                raise
            if span.recording:
                span.set_attribute("http.status", response.status)
                if response.status >= 500:
                    span.status = "error"
            stamp(response)
            return response

    app = web.Application(
        middlewares=[request_context_middleware], client_max_size=256 * 2**20
    )
    routes = web.RouteTableDef()

    def bad_request(message, **extra) -> web.Response:
        return web.json_response({"error": message, **extra}, status=400)

    def with_trace_id(body: dict) -> dict:
        """Error bodies carry the trace id too: a shed/degraded response is
        exactly the request an operator wants to pull the trace for."""
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            body["trace_id"] = trace_id
        return body

    def shed(e: CircuitOpenError) -> web.Response:
        """Load-shedding response while a lane's breaker is open: 503 +
        Retry-After (degraded SERVICE — distinct from 429, which means the
        service is healthy but THIS caller hit a capacity cap)."""
        retry_after = max(1, math.ceil(e.retry_after or 1.0))
        return web.json_response(
            with_trace_id({"error": str(e), "degraded": True}),
            status=503,
            headers={"Retry-After": str(retry_after)},
        )

    async def parse_model(request: web.Request, model):
        try:
            return model.model_validate(await request.json())
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "invalid JSON body"}),
                content_type="application/json",
            )
        except ValidationError as e:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "validation failed", "detail": e.errors(include_url=False)}),
                content_type="application/json",
            )

    @routes.get("/healthz")
    async def healthz(request: web.Request) -> web.Response:
        if code_executor.draining:
            # Graceful shutdown in progress: load balancers must stop
            # sending work here while in-flight executes finish.
            return web.json_response(
                {"status": "draining", "reason": "service is shutting down"},
                status=503,
            )
        if code_executor.degraded():
            retry_after = max(1, math.ceil(code_executor.degraded_retry_after() or 1.0))
            return web.json_response(
                {
                    "status": "degraded",
                    "reason": "default-lane spawn circuit open",
                },
                status=503,
                headers={"Retry-After": str(retry_after)},
            )
        # Operator detail: per-lane queue pressure (the scheduler's own
        # queue-wait EWMA — no longer just a hint: the warm-pool
        # autoscaler closes the loop on it) and batch occupancy ("are
        # batches running under-filled?"), joined with SUPPLY (the dynamic
        # pool target and the pooled/in-use/spawning counts backing it) so
        # demand and supply read side by side.
        body: dict = {"status": "ok"}
        lanes = code_executor.scheduler.lane_detail()
        for lane, entry in code_executor.lane_supply().items():
            lanes.setdefault(lane, {}).update(entry)
        if lanes:
            body["lanes"] = lanes
        body["batching"] = {
            "enabled": code_executor.batcher is not None,
            "window_ms": code_executor.config.batch_window_ms,
            "max_jobs": code_executor.config.batch_max_jobs,
        }
        return web.json_response(body)

    @routes.get("/metrics")
    async def metrics(request: web.Request) -> web.Response:
        # The versioned Content-Type is part of the exposition contract
        # (Prometheus text format 0.0.4); a bare text/plain reads as an
        # unversioned payload to strict scrapers.
        return web.Response(
            body=code_executor.metrics.registry.render().encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    def paging_params(
        request: web.Request, *, default_limit: int, max_limit: int
    ) -> tuple[int, int]:
        """Shared `?limit=`/`?offset=` parsing with hard caps: the trace
        debug surfaces page through bounded responses — a full TraceRing
        must never become one multi-megabyte reply."""
        try:
            limit = int(request.query.get("limit", str(default_limit)))
            offset = int(request.query.get("offset", "0"))
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "limit/offset must be integers"}),
                content_type="application/json",
            )
        return max(0, min(limit, max_limit)), max(0, offset)

    @routes.get("/traces")
    async def recent_traces(request: web.Request) -> web.Response:
        """Debug surface: newest traces still in the in-memory ring
        (trace id, root span, span count, errors). `?limit=`/`?offset=`
        page the list (hard cap per response)."""
        limit, offset = paging_params(request, default_limit=20, max_limit=200)
        return web.json_response(
            {
                "enabled": tracer.enabled,
                "sample_ratio": tracer.sample_ratio,
                "limit": limit,
                "offset": offset,
                "traces": tracer.ring.recent(limit=limit, offset=offset),
            }
        )

    @routes.get("/traces/{trace_id}")
    async def get_trace(request: web.Request) -> web.Response:
        """One trace's retained spans in start order. `?format=jsonl` gets
        the export format (one span per line) instead of the JSON tree;
        `?limit=`/`?offset=` page the span list (a 100%-sampled trace can
        hold thousands of spans — `total_spans` says when to page)."""
        trace_id = request.match_info["trace_id"].lower()
        if not TRACE_ID_RE.match(trace_id):
            return bad_request("invalid trace id (want 32 hex chars)")
        limit, offset = paging_params(
            request, default_limit=500, max_limit=2000
        )
        spans = tracer.ring.trace(trace_id)
        if not spans:
            return web.json_response(
                {"error": "trace not found (expired from the ring, "
                          "unsampled, or never existed)"},
                status=404,
            )
        total = len(spans)
        page = spans[offset : offset + limit]
        if request.query.get("format") == "jsonl":
            # NDJSON has no envelope for paging state, so truncation rides
            # the headers: a consumer seeing X-Total-Spans > its line count
            # knows to page with ?offset= — the export must never LOOK
            # complete when it isn't.
            text = "".join(
                json.dumps(span, sort_keys=True) + "\n" for span in page
            )
            return web.Response(
                text=text,
                content_type="application/x-ndjson",
                headers={
                    "X-Total-Spans": str(total),
                    "X-Limit": str(limit),
                    "X-Offset": str(offset),
                },
            )
        return web.json_response(
            {
                "trace_id": trace_id,
                "total_spans": total,
                "limit": limit,
                "offset": offset,
                "spans": page,
            }
        )

    @routes.get("/statusz")
    async def statusz(request: web.Request) -> web.Response:
        """Consolidated operator status: lanes (queue pressure, pool depth,
        batch occupancy, breaker state), every live host with its
        device-health verdict, sessions, compile-cache store stats, and
        the telemetry plane's own health — one endpoint for the question
        "is this fleet OK, and if not, which host is the problem?"."""
        body = code_executor.statusz()
        if request.query.get("format") == "text":
            return web.Response(text=statusz_text(body))
        return web.json_response(body)

    @routes.get("/usage")
    async def usage(request: web.Request) -> web.Response:
        """Per-tenant usage accounting: every tenant's cumulative
        chip-seconds, queue wait, transfer bytes, recompiles, violations,
        and request/batch-job counts, straight from the durable ledger
        (services/usage.py). `?format=text` renders the operator view.
        With the metering kill switch off this surface answers 404 —
        pre-metering behavior, byte-for-byte."""
        if not code_executor.usage.enabled:
            return web.json_response(
                {"error": "usage metering is disabled "
                          "(APP_USAGE_METERING_ENABLED=0)"},
                status=404,
            )
        body = code_executor.usage.snapshot()
        if request.query.get("format") == "text":
            return web.Response(text=usage_text(body))
        return web.json_response(body)

    @routes.get("/usage/{tenant}")
    async def usage_tenant(request: web.Request) -> web.Response:
        """One tenant's ledger row. A tenant past the cardinality cap
        accrues under `_overflow` — query that row for the aggregate."""
        if not code_executor.usage.enabled:
            return web.json_response(
                {"error": "usage metering is disabled "
                          "(APP_USAGE_METERING_ENABLED=0)"},
                status=404,
            )
        tenant = request.match_info["tenant"]
        row = code_executor.usage.tenant_snapshot(tenant)
        if row is None:
            return web.json_response(
                {"error": f"no usage recorded for tenant {tenant!r}"},
                status=404,
            )
        body = {"tenant": tenant, "usage": row}
        if request.query.get("format") == "text":
            return web.Response(text=_usage_row_text(tenant, row) + "\n")
        return web.json_response(body)

    @routes.get("/quotas")
    async def quotas(request: web.Request) -> web.Response:
        """The quota layer's verdict state: default policy, per-tenant
        window consumption vs budget, in-flight counts, quarantine
        sentences, and denial totals (services/quotas.py). `?format=text`
        renders the operator view. 404 with the kill switch off —
        pre-quota behavior, byte-for-byte."""
        if not code_executor.quotas.enabled:
            return web.json_response(
                {"error": "quota enforcement is disabled "
                          "(APP_QUOTAS_ENABLED=0, or usage metering is off)"},
                status=404,
            )
        body = code_executor.quotas.snapshot()
        if request.query.get("format") == "text":
            return web.Response(text=quotas_text(body))
        return web.json_response(body)

    @routes.get("/quotas/{tenant}")
    async def quotas_tenant(request: web.Request) -> web.Response:
        """One tenant's quota view. A tenant past the ledger's cardinality
        cap shares the `_overflow` row's budget — query that row for the
        aggregate, exactly like /usage/{tenant}."""
        if not code_executor.quotas.enabled:
            return web.json_response(
                {"error": "quota enforcement is disabled "
                          "(APP_QUOTAS_ENABLED=0, or usage metering is off)"},
                status=404,
            )
        tenant = request.match_info["tenant"]
        row = code_executor.quotas.tenant_snapshot(tenant)
        if row is None:
            return web.json_response(
                {"error": f"no quota state for tenant {tenant!r}"},
                status=404,
            )
        body = {"tenant": tenant, "quota": row}
        if request.query.get("format") == "text":
            return web.Response(text=_quota_row_text(tenant, row) + "\n")
        return web.json_response(body)

    @routes.get("/perf")
    async def perf(request: web.Request) -> web.Response:
        """The performance anomaly plane's verdicts: per-(lane, phase)
        latency quantiles with their EWMA baselines and drift states
        (normal/degraded/regressed), per-tenant latency series, and the
        auto-profiling state (services/perf_observer.py). `?format=text`
        renders the operator view. 404 with the kill switch off —
        today's surface set, byte-for-byte."""
        if not code_executor.perf.enabled:
            return web.json_response(
                {"error": "perf observer is disabled "
                          "(APP_PERF_OBSERVER_ENABLED=0)"},
                status=404,
            )
        body = code_executor.perf.snapshot()
        if request.query.get("format") == "text":
            return web.Response(text=perf_text(body))
        return web.json_response(body)

    @routes.get("/profiles")
    async def profiles(request: web.Request) -> web.Response:
        """Auto-captured profile artifacts: id, trigger reason, lane,
        tenant, trace-id cross-link, size, capture time — newest first.
        `?limit=`/`?offset=` page the list, and the X-Total-* headers
        signal truncation (the /traces jsonl discipline: a paged listing
        must never LOOK complete when it isn't)."""
        store = code_executor.perf.store
        if not code_executor.perf.enabled or store is None:
            return web.json_response(
                {"error": "perf observer is disabled "
                          "(APP_PERF_OBSERVER_ENABLED=0)"},
                status=404,
            )
        limit, offset = paging_params(request, default_limit=50, max_limit=500)
        rows = store.list()
        total = len(rows)
        return web.json_response(
            {
                "total": total,
                "limit": limit,
                "offset": offset,
                "profiles": rows[offset : offset + limit],
            },
            headers={
                "X-Total-Profiles": str(total),
                "X-Limit": str(limit),
                "X-Offset": str(offset),
            },
        )

    @routes.get("/profiles/{profile_id}")
    async def get_profile(request: web.Request) -> web.Response:
        """One harvested profile's zip bytes (the JAX profiler trace an
        operator feeds to TensorBoard/xprof), with its capture meta in
        headers — X-Trace-Id links back to the triggering request's
        /traces entry."""
        store = code_executor.perf.store
        if not code_executor.perf.enabled or store is None:
            return web.json_response(
                {"error": "perf observer is disabled "
                          "(APP_PERF_OBSERVER_ENABLED=0)"},
                status=404,
            )
        profile_id = request.match_info["profile_id"]
        if not OBJECT_ID_RE.match(profile_id):
            return bad_request("invalid profile id")
        found = store.get(profile_id)
        if found is None:
            return web.json_response(
                {"error": f"no profile {profile_id!r} (evicted or never "
                          "captured)"},
                status=404,
            )
        data, meta = found
        headers = {
            "Content-Disposition": (
                f'attachment; filename="profile-{profile_id}.zip"'
            ),
        }
        if meta.get("trace_id"):
            headers["X-Trace-Id"] = str(meta["trace_id"])
        if meta.get("reason"):
            headers["X-Profile-Trigger"] = str(meta["reason"])
        return web.Response(
            body=data, content_type="application/zip", headers=headers
        )

    @routes.get("/profiles/{profile_id}/summary")
    async def get_profile_summary(request: web.Request) -> web.Response:
        """An xprof verdict instead of a raw zip: top device ops, device-op
        wall share, and the largest idle gaps, parsed from the profile's
        trace-event JSON (services/perf_observer.py:summarize_profile).
        Artifacts without a parseable trace degrade to a member listing."""
        store = code_executor.perf.store
        if not code_executor.perf.enabled or store is None:
            return web.json_response(
                {"error": "perf observer is disabled "
                          "(APP_PERF_OBSERVER_ENABLED=0)"},
                status=404,
            )
        profile_id = request.match_info["profile_id"]
        if not OBJECT_ID_RE.match(profile_id):
            return bad_request("invalid profile id")
        found = store.get(profile_id)
        if found is None:
            return web.json_response(
                {"error": f"no profile {profile_id!r} (evicted or never "
                          "captured)"},
                status=404,
            )
        data, meta = found
        summary = summarize_profile(data)
        body = {"id": profile_id, "meta": meta, **summary}
        headers = {}
        if meta.get("trace_id"):
            headers["X-Trace-Id"] = str(meta["trace_id"])
        return web.json_response(body, headers=headers)

    def validate_execute(req: ExecuteRequest) -> web.Response | None:
        """Shared /v1/execute + /v1/execute/stream pre-flight checks."""
        if (req.source_code is None) == (req.source_file is None):
            return bad_request("exactly one of source_code/source_file is required")
        for path, object_id in req.files.items():
            if not OBJECT_ID_RE.match(object_id):
                return bad_request(f"invalid file object id for {path}")
        return None

    def admission_params(request: web.Request, req: ExecuteRequest) -> dict:
        """Tenant/priority/deadline for the scheduler: body fields first,
        headers as fallback. Value validation (tenant charset, priority
        names) lives in the scheduler — its ValueError maps to 400 on the
        same path as every other client error."""
        tenant = req.tenant or request.headers.get("X-Tenant")
        priority = req.priority or request.headers.get("X-Priority")
        deadline = req.deadline
        if deadline is None:
            raw = request.headers.get("X-Deadline-Seconds")
            if raw is not None:
                try:
                    deadline = float(raw)
                except ValueError:
                    raise web.HTTPBadRequest(
                        text=json.dumps(
                            {"error": "X-Deadline-Seconds must be a number"}
                        ),
                        content_type="application/json",
                    )
        return {"tenant": tenant, "priority": priority, "deadline": deadline}

    def limits_param(request: web.Request, req: ExecuteRequest) -> dict | None:
        """Per-request resource-budget override: body field first, the
        X-Sandbox-Limits header (JSON object) as the gateway fallback.
        Value/key validation lives in services.limits — its ValueError maps
        to 400 on the same path as every other client error."""
        if req.limits is not None:
            return req.limits
        raw = request.headers.get("X-Sandbox-Limits")
        if raw is None:
            return None
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(
                text=json.dumps(
                    {"error": "X-Sandbox-Limits must be a JSON object"}
                ),
                content_type="application/json",
            )
        return parsed

    def violation_response(e: LimitExceededError) -> web.Response:
        """422 for typed limit violations: the request was well-formed but
        unprocessable within its resource budget. Deterministic — clients
        must not blind-retry (no Retry-After on purpose); the body names
        the violated limit so they can raise their budget or fix the
        snippet."""
        return web.json_response(
            with_trace_id({"error": str(e), "violation": e.kind}),
            status=422,
        )

    def capacity_response(e: SessionLimitError) -> web.Response:
        """429 for capacity rejections. Admission sheds carry a computed
        Retry-After (queue-depth/EWMA-derived) — surface it as the header so
        clients back off proportionally to the actual backlog."""
        headers = {}
        retry_after = getattr(e, "retry_after", 0.0)
        if retry_after:
            headers["Retry-After"] = str(max(1, math.ceil(retry_after)))
        return web.json_response(
            with_trace_id({"error": str(e)}), status=429, headers=headers
        )

    def quota_response(e: QuotaExceededError) -> web.Response:
        """429 for quota denials — the same retryable family as every
        capacity shed (client retry loops need no new branch), but typed:
        the Retry-After is computed from the WINDOW's refill point (or the
        quarantine sentence), and the X-Quota-* headers carry the reason
        and the remaining budget so a pacing client can distinguish "slow
        down" (chip_seconds/request_rate), "narrow down" (concurrency),
        and "stop violating limits" (quarantined)."""
        headers = {
            "Retry-After": str(max(1, math.ceil(e.retry_after or 1.0))),
            "X-Quota-Reason": e.reason,
        }
        body: dict = {
            "error": str(e),
            "quota": {"tenant": e.tenant, "reason": e.reason,
                      "retry_after_s": round(e.retry_after, 3)},
        }
        if e.remaining_chip_seconds is not None:
            headers["X-Quota-Remaining-Chip-Seconds"] = (
                f"{e.remaining_chip_seconds:.6f}"
            )
            body["quota"]["remaining_chip_seconds"] = round(
                e.remaining_chip_seconds, 6
            )
        if e.limit_chip_seconds is not None:
            headers["X-Quota-Limit-Chip-Seconds"] = (
                f"{e.limit_chip_seconds:.6f}"
            )
            body["quota"]["limit_chip_seconds"] = round(
                e.limit_chip_seconds, 6
            )
        if e.window_seconds is not None:
            headers["X-Quota-Window-Seconds"] = f"{e.window_seconds:.3f}"
            body["quota"]["window_seconds"] = round(e.window_seconds, 3)
        if getattr(e, "remaining_hbm_byte_seconds", None) is not None:
            headers["X-Quota-Remaining-Hbm-Byte-Seconds"] = (
                f"{e.remaining_hbm_byte_seconds:.3f}"
            )
            body["quota"]["remaining_hbm_byte_seconds"] = round(
                e.remaining_hbm_byte_seconds, 3
            )
        if getattr(e, "limit_hbm_byte_seconds", None) is not None:
            headers["X-Quota-Limit-Hbm-Byte-Seconds"] = (
                f"{e.limit_hbm_byte_seconds:.3f}"
            )
            body["quota"]["limit_hbm_byte_seconds"] = round(
                e.limit_hbm_byte_seconds, 3
            )
        if getattr(e, "burst_credits_remaining", None) is not None:
            headers["X-Quota-Burst-Credits"] = (
                f"{e.burst_credits_remaining:.6f}"
            )
            body["quota"]["burst_credits_remaining"] = round(
                e.burst_credits_remaining, 6
            )
        return web.json_response(
            with_trace_id(body), status=429, headers=headers
        )

    def stale_lease_response(e: StaleLeaseError) -> web.Response:
        """409 for a stale-lease refusal that made it all the way to the
        client (sessions, which never retry; the stateless path replays on
        a fresh sandbox first): the request's host was fenced mid-flight.
        Retryable — a fresh request lands on a healthy host — so the 409
        carries a Retry-After, and the typed reason lets a session client
        distinguish "reconnect" from a genuine conflict."""
        return web.json_response(
            with_trace_id({"error": str(e), "reason": "stale_lease"}),
            status=409,
            headers={
                "Retry-After": str(
                    max(1, math.ceil(getattr(e, "retry_after", 1.0) or 1.0))
                )
            },
        )

    def session_restoring_response(e: SessionRestoringError) -> web.Response:
        """409 for a turn that raced a restore-in-flight: another turn is
        rehydrating this session from its durable checkpoint right now.
        The stale-lease 409 family on purpose — typed reason + Retry-After,
        so a session client's existing 409 retry loop needs no new branch
        and the retry lands after the restore completes."""
        return web.json_response(
            with_trace_id({"error": str(e), "reason": "session_restoring"}),
            status=409,
            headers={
                "Retry-After": str(
                    max(1, math.ceil(getattr(e, "retry_after", 1.0) or 1.0))
                )
            },
        )

    def store_degraded_response(e: StateStoreDegradedError) -> web.Response:
        """503 for a request refused because the shared control-plane store
        is unreachable and the touched subsystem fails CLOSED (lease mints,
        session hibernate/restore). Deliberately NOT a 502: nothing is
        wrong with the request or the sandbox fleet — the store outage is
        transient, so the typed reason + Retry-After tells clients to back
        off and retry rather than fail over or alert."""
        return web.json_response(
            with_trace_id(
                {
                    "error": str(e),
                    "reason": "store_degraded",
                    "subsystem": getattr(e, "subsystem", "") or "",
                    "retry_after_s": round(
                        float(getattr(e, "retry_after", 5.0) or 5.0), 3
                    ),
                }
            ),
            status=503,
            headers={
                "Retry-After": str(
                    max(1, math.ceil(getattr(e, "retry_after", 5.0) or 5.0))
                )
            },
        )

    def add_session_fields(body: dict, result, executor_id: str | None) -> dict:
        """Session continuity, one rule for every surface: seq==1 on a
        request the client expected to land in an existing session means
        prior state was lost (idle expiry); session_ended means THIS request
        killed the session."""
        if executor_id and result is not None:
            body["session_seq"] = result.session_seq
            body["session_ended"] = result.session_ended
        return body

    def result_body(result, req: ExecuteRequest) -> dict:
        """Execute response body, identical for both surfaces (the stream's
        final event must never diverge from the non-streaming body)."""
        body = {
            "stdout": result.stdout,
            "stderr": result.stderr,
            "exit_code": result.exit_code,
            "files": result.files,
            "phases": result.phases,
            "warm": result.warm,
            "stdout_truncated": result.stdout_truncated,
            "stderr_truncated": result.stderr_truncated,
        }
        return add_session_fields(body, result, req.executor_id)

    def memo_header(result) -> dict[str, str]:
        """The X-Memo response header: the memo verdict for declared-pure
        requests (hit|miss|bypass, from the phases block the executor
        stamped). No header when the run didn't declare purity or the memo
        kill switch is off — pre-memo responses byte-for-byte."""
        memo = result.phases.get("memo")
        if isinstance(memo, dict) and isinstance(memo.get("state"), str):
            return {"X-Memo": memo["state"]}
        return {}

    @routes.post("/v1/execute")
    async def execute(request: web.Request) -> web.Response:
        req = await parse_model(request, ExecuteRequest)
        if (error := validate_execute(req)) is not None:
            return error
        routed = await route_session(
            request, session_tenant(request, req), req.executor_id
        )
        if routed is not None:
            return routed
        try:
            result = await code_executor.execute(
                req.source_code,
                source_file=req.source_file,
                files=req.files,
                timeout=req.timeout,
                env=req.env,
                chip_count=req.chip_count,
                profile=req.profile,
                executor_id=req.executor_id,
                limits=limits_param(request, req),
                pure=req.pure,
                **admission_params(request, req),
            )
        except ValueError as e:
            return bad_request(str(e))
        except CircuitOpenError as e:
            return shed(e)
        except LimitExceededError as e:
            return violation_response(e)
        except QuotaExceededError as e:
            # Quota denial (before SessionLimitError: it subclasses it) —
            # 429 with the window-derived Retry-After and X-Quota-* headers.
            return quota_response(e)
        except SessionLimitError as e:
            # Resource exhaustion, not a request defect: retryable.
            return capacity_response(e)
        except SessionRestoringError as e:
            # Before ExecutorError (its parent): a concurrent turn owns the
            # session's restore — typed 409 + Retry-After, retry lands
            # after the restore completes.
            return session_restoring_response(e)
        except StaleLeaseError as e:
            # Before ExecutorError (its parent): the host was fenced —
            # typed 409 + Retry-After, the client reconnects to a healthy
            # host.
            return stale_lease_response(e)
        except StateStoreDegradedError as e:
            # The shared store is down and this request needed a
            # fail-closed subsystem (lease mint, session restore) —
            # typed 503 + Retry-After, retry lands after the store heals.
            return store_degraded_response(e)
        except (ExecutorError, SandboxSpawnError) as e:
            logger.exception("execute failed")
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response(
            result_body(result, req), headers=memo_header(result)
        )

    @routes.post("/v1/execute/stream")
    async def execute_stream(request: web.Request) -> web.StreamResponse:
        """Streaming Execute: chunked NDJSON — {"stream","data"} events while
        the code runs, then a final object with the full execute response
        body. Pre-flight errors use plain JSON statuses; a mid-stream
        failure emits a final {"error": ...} line (headers are already
        gone)."""
        req = await parse_model(request, ExecuteRequest)
        if (error := validate_execute(req)) is not None:
            return error
        routed = await route_session(
            request, session_tenant(request, req), req.executor_id
        )
        if routed is not None:
            return routed
        events = code_executor.execute_stream(
            req.source_code,
            source_file=req.source_file,
            files=req.files,
            timeout=req.timeout,
            env=req.env,
            chip_count=req.chip_count,
            profile=req.profile,
            executor_id=req.executor_id,
            limits=limits_param(request, req),
            pure=req.pure,
            **admission_params(request, req),
        )
        # Correlation headers must land BEFORE prepare() on a stream (the
        # middleware can only stamp unprepared responses).
        stream_headers = {"Content-Type": "application/x-ndjson"}
        stream_headers["X-Request-Id"] = request_id_var.get()
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            stream_headers["X-Trace-Id"] = trace_id
        response = web.StreamResponse(status=200, headers=stream_headers)
        # Chunked implicitly (no Content-Length); flush per event so clients
        # see output with the code's own cadence.
        started = False
        try:
            async for event in events:
                if "result" in event:
                    payload = result_body(event["result"], req)
                else:
                    payload = event
                if not started:
                    await response.prepare(request)
                    started = True
                await response.write(
                    (json.dumps(payload) + "\n").encode("utf-8")
                )
        except ValueError as e:
            if not started:
                return bad_request(str(e))
            await response.write(
                (json.dumps({"error": str(e)}) + "\n").encode("utf-8")
            )
        except CircuitOpenError as e:
            if not started:
                return shed(e)
            await response.write(
                (json.dumps({"error": str(e)}) + "\n").encode("utf-8")
            )
        except LimitExceededError as e:
            # Mid-stream the violation rides the final NDJSON event (the
            # output already streamed is exactly what ran before the kill).
            if not started:
                return violation_response(e)
            await response.write(
                (
                    json.dumps({"error": str(e), "violation": e.kind}) + "\n"
                ).encode("utf-8")
            )
        except QuotaExceededError as e:
            if not started:
                return quota_response(e)
            await response.write(
                (
                    json.dumps({"error": str(e), "quota_reason": e.reason})
                    + "\n"
                ).encode("utf-8")
            )
        except SessionLimitError as e:
            if not started:
                return capacity_response(e)
            await response.write(
                (json.dumps({"error": str(e)}) + "\n").encode("utf-8")
            )
        except SessionRestoringError as e:
            # Before ExecutorError (its parent): restore-in-flight refusal.
            if not started:
                return session_restoring_response(e)
            await response.write(
                (
                    json.dumps({"error": str(e), "reason": "session_restoring"})
                    + "\n"
                ).encode("utf-8")
            )
        except StaleLeaseError as e:
            # Before ExecutorError (its parent): typed fence refusal.
            if not started:
                return stale_lease_response(e)
            await response.write(
                (
                    json.dumps({"error": str(e), "reason": "stale_lease"})
                    + "\n"
                ).encode("utf-8")
            )
        except StateStoreDegradedError as e:
            # Fail-closed store refusal: typed 503 pre-stream, final
            # typed event once headers are gone.
            if not started:
                return store_degraded_response(e)
            await response.write(
                (
                    json.dumps({"error": str(e), "reason": "store_degraded"})
                    + "\n"
                ).encode("utf-8")
            )
        except (ExecutorError, SandboxSpawnError) as e:
            logger.exception("execute stream failed")
            if not started:
                return web.json_response({"error": str(e)}, status=502)
            await response.write(
                (json.dumps({"error": str(e)}) + "\n").encode("utf-8")
            )
        await response.write_eof()
        return response

    @routes.get("/v1/executors")
    async def list_executor_sessions(request: web.Request) -> web.Response:
        """Live executor_id sessions: id, chip lane, idle seconds, busy flag,
        requests served — the operator's view of what is parking sandboxes."""
        return web.json_response({"sessions": code_executor.list_sessions()})

    @routes.delete("/v1/executors/{executor_id}")
    async def close_executor_session(request: web.Request) -> web.Response:
        """End an executor_id session: waits out an in-flight request, then
        releases the sandbox (its workspace is discarded; files already
        round-tripped through /v1/files or Execute responses survive).

        Replicated deployments: DELETE has no body, so the affinity key's
        tenant half comes from X-Tenant ALONE — a session created with a
        body tenant must pass the same tenant as X-Tenant here, or the
        key hashes to the wrong replica (the 404 body reminds; the idle
        sweeper bounds the cost of a missed close either way)."""
        executor_id = request.match_info["executor_id"]
        if not OBJECT_ID_RE.match(executor_id):
            return bad_request("invalid executor_id")
        routed = await route_session(
            request, session_tenant(request), executor_id
        )
        if routed is not None:
            return routed
        try:
            closed = await code_executor.close_session(
                executor_id, tenant=session_tenant(request)
            )
        except StateStoreDegradedError as e:
            # A hibernated session's record lives in the shared store; with
            # the store down the close cannot prove (or destroy) it — the
            # typed 503 beats silently reporting "no such session".
            return store_degraded_response(e)
        if closed:
            return web.json_response({"closed": executor_id})
        body = {"error": "no such session"}
        if router is not None and len(router.ring.peers) > 1:
            body["hint"] = (
                "replicated deployment: a session created with a body "
                "tenant routes by that tenant — pass it as X-Tenant on "
                "DELETE (idle sweep reclaims missed closes)"
            )
        return web.json_response(body, status=404)

    @routes.post("/v1/parse-custom-tool")
    async def parse_custom_tool(request: web.Request) -> web.Response:
        req = await parse_model(request, ParseCustomToolRequest)
        try:
            tool = custom_tool_executor.parse(req.tool_source_code)
        except CustomToolParseError as e:
            return web.json_response({"error_messages": e.errors}, status=400)
        return web.json_response(
            {
                "tool_name": tool.name,
                "tool_description": tool.description,
                "tool_input_schema_json": json.dumps(tool.input_schema),
            }
        )

    @routes.post("/v1/execute-custom-tool")
    async def execute_custom_tool(request: web.Request) -> web.Response:
        req = await parse_model(request, ExecuteCustomToolRequest)
        routed = await route_session(
            request, session_tenant(request, req), req.executor_id
        )
        if routed is not None:
            return routed
        try:
            tool_input = json.loads(req.tool_input_json)
        except json.JSONDecodeError:
            return bad_request("tool_input_json is not valid JSON")
        try:
            output, exec_result = await custom_tool_executor.execute_with_result(
                req.tool_source_code,
                tool_input,
                executor_id=req.executor_id,
                timeout=req.timeout,
            )
        except CustomToolParseError as e:
            return web.json_response({"error_messages": e.errors}, status=400)
        except CustomToolExecuteError as e:
            # Continuity on failure too: a timeout that killed the session
            # must be visible even though the tool call itself failed.
            return web.json_response(
                add_session_fields({"stderr": e.stderr}, e.result, req.executor_id),
                status=400,
            )
        except ValueError as e:
            return bad_request(str(e))
        except CircuitOpenError as e:
            return shed(e)
        except LimitExceededError as e:
            return violation_response(e)
        except QuotaExceededError as e:
            return quota_response(e)
        except SessionLimitError as e:
            return capacity_response(e)
        except StateStoreDegradedError as e:
            return store_degraded_response(e)
        except (ExecutorError, SandboxSpawnError) as e:
            logger.exception("custom tool execute failed")
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response(
            add_session_fields(
                {"tool_output_json": json.dumps(output)}, exec_result, req.executor_id
            )
        )

    @routes.put("/v1/files")
    async def upload_file(request: web.Request) -> web.Response:
        # multipart/form-data with a `file` part, or a raw body
        object_id: str | None = None
        if request.content_type.startswith("multipart/"):
            reader = await request.multipart()
            part = await reader.next()
            while part is not None and part.name != "file":
                part = await reader.next()
            if part is None:
                return bad_request("multipart body must contain a 'file' part")
            async with storage.writer() as writer:
                while chunk := await part.read_chunk(1 << 20):
                    await writer.write(chunk)
            object_id = writer.hash
        else:
            async with storage.writer() as writer:
                async for chunk in request.content.iter_chunked(1 << 20):
                    await writer.write(chunk)
            object_id = writer.hash
        return web.json_response({"hash": object_id})

    @routes.get("/v1/files/{hash}")
    async def download_file(request: web.Request) -> web.StreamResponse:
        object_id = request.match_info["hash"]
        if not OBJECT_ID_RE.match(object_id):
            return bad_request("invalid object id")
        delete_after = request.query.get("delete", "").lower() in ("1", "true", "yes")
        # Open the reader BEFORE preparing the response: once headers are sent
        # a late StorageObjectNotFound could no longer become a clean 404 (and
        # an open fd keeps the content alive even if a concurrent delete wins).
        reader_cm = storage.reader(object_id)
        try:
            reader = await reader_cm.__aenter__()
        except StorageObjectNotFound:
            return web.json_response({"error": "file not found"}, status=404)
        try:
            size = os.fstat(reader.wrapped.fileno()).st_size
            response = web.StreamResponse(
                status=200,
                headers={
                    "Content-Type": "application/octet-stream",
                    "Content-Length": str(size),
                },
            )
            await response.prepare(request)
            while chunk := await reader.read(1 << 20):
                await response.write(chunk)
            await response.write_eof()
        finally:
            await reader_cm.__aexit__(None, None, None)
        if delete_after:
            await storage.delete(object_id)
        return response

    @routes.delete("/v1/files/{hash}")
    async def delete_file(request: web.Request) -> web.Response:
        object_id = request.match_info["hash"]
        if not OBJECT_ID_RE.match(object_id):
            return bad_request("invalid object id")
        await storage.delete(object_id)
        return web.json_response({"deleted": object_id})

    app.add_routes(routes)
    return app
