"""Demand-adaptive warm-pool autoscaling: queue-wait-driven lane targets.

Before this subsystem every lane's warm-pool depth was one static knob
(`executor_pod_queue_target_length = 5`): under a burst the queue grew until
spawns caught up one acquire at a time, and off-peak an idle lane squatted
warm chips a pressured lane on shared physical capacity could not claim.
The ROADMAP scale-out item names the fix — close the loop on the
`scheduler_queue_wait_ewma_seconds` gauge (PR 3) by driving warm-pool
capacity from it — and the Kubernetes GenAI-inference evaluation (PAPERS.md,
arxiv 2602.04900) grounds the pattern: queue-wait-driven pool scaling is
what holds p50 under bursty serving traffic on a pod-per-request plane,
while Podracer's lesson (arxiv 2104.06272) is the same from the chip side —
accelerators must never idle behind static partitioning.

This module owns the POLICY only; `CodeExecutor` owns the bookkeeping and
the actuators (fill_pool for scale-up, the idle reaper for scale-down) and
feeds the model `LaneSnapshot`s:

- **Demand model** — per lane, ``raw = in_use + queued + arrival_rate x
  spawn_latency (+ queue-wait pressure headroom)``. The arrival-rate EWMA
  makes scale-up *spawn-ahead*: refills start when backlog x spawn-time
  says demand will outrun supply, not when a request is already waiting.
  The rate estimate is additionally bounded by ``1 / time-since-last-
  arrival`` so a stale burst's rate decays the moment traffic stops.
- **Queue-wait loop** — while the scheduler's smoothed grant wait exceeds
  `pool_target_queue_wait`, the model adds proportional headroom: sustained
  waiting means supply has been lagging even when the instantaneous counts
  look covered.
- **Asymmetric dynamics** — scale-UP applies immediately (on the arrival
  path, before the request even queues); scale-DOWN needs demand below the
  current target for `pool_scale_down_after` continuous seconds and then
  steps one notch per evaluation — hysteresis, so a lull between waves
  never flaps the pool. Spawn faults cannot oscillate the target either:
  supply is not an input to the model, only demand is.
- **Kill switch** — `APP_POOL_AUTOSCALE_ENABLED=0` makes `target()` return
  the static constant for every lane, restoring pre-autoscale behavior
  byte-for-byte. A static target of 0 ("no warm pool") is honored verbatim
  in BOTH modes: deployments that explicitly disabled pooling must not
  gain one because a model started running.

Targets are *desired warm capacity*; the executor still clamps them under
the backend's physical `pool_capacity` (and the session-held slots) in
`_lane_target` — cross-lane arbitration over shared chips stays where the
capacity truth lives.

The clock is injectable, so the whole dynamics suite runs on a fake clock
with zero sleeps (the scheduler's discipline).
"""

from __future__ import annotations

import logging
import math
import time
from collections.abc import Callable
from dataclasses import dataclass

from ..config import Config
from ..utils import tracing

logger = logging.getLogger(__name__)

SCALE_UP = "up"
SCALE_DOWN = "down"
SCALE_REAP = "reap"


@dataclass
class LaneSnapshot:
    """One lane's supply/demand instant, assembled by the executor.

    `pooled` counts only SERVABLE warm sandboxes: wedged, draining
    (fenced, dispose in flight), and recovering (fenced scope, earning its
    clean-probe re-admission streak) hosts read as empty supply, so the
    model keeps demanding replacements for the first two. `recovering` is
    broken out separately because it is supply-IN-TRANSIT — those hosts
    hold their chips and re-admit shortly, so refill decisions must count
    them (spawning past them would overshoot and, on constrained lanes,
    deadlock on the chips they still own); `draining` is pure
    observability (the statusz/healthz rows)."""

    queued: int = 0
    in_use: int = 0
    pooled: int = 0
    spawning: int = 0
    recovering: int = 0
    draining: int = 0
    queue_wait_ewma: float = 0.0
    spawn_ewma: float = 0.0
    # Hibernated sessions whose wake would land on this lane (the session
    # store's per-lane index count): supply the durability plane RECLAIMED
    # that may come asking for a chip back. An explicit demand signal —
    # weighted into raw_demand by pool_hibernated_wake_weight (default 0:
    # visible in statusz, absent from the targets).
    hibernated: int = 0


class _LaneModel:
    """Per-lane dynamic state: the current target plus the demand
    estimators behind it."""

    __slots__ = (
        "target",
        "arrival_rate",
        "last_arrival",
        "below_since",
        "last_raw",
        "last_hibernated",
        "scale_ups",
        "scale_downs",
        "reaped",
    )

    def __init__(self, target: int) -> None:
        self.target = target
        self.arrival_rate: float | None = None  # requests/s EWMA
        self.last_arrival: float | None = None
        self.below_since: float | None = None  # demand < target since (s)
        self.last_raw = 0.0
        self.last_hibernated = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.reaped = 0


class PoolAutoscaler:
    """Queue-wait-driven per-lane warm-pool targets (policy half)."""

    def __init__(
        self,
        config: Config | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        tracer=None,
    ) -> None:
        self.config = config or Config()
        self.clock = clock
        self.metrics = metrics
        self.tracer = tracer
        self.enabled = bool(self.config.pool_autoscale_enabled)
        self.min_target = max(0, self.config.pool_min_target)
        self.max_target = max(self.min_target, self.config.pool_max_target)
        # EWMA smoothing shared with the scheduler's estimators: one knob,
        # one notion of "reacts this fast".
        self._alpha = min(max(self.config.scheduler_ewma_alpha, 0.01), 1.0)
        self._lanes: dict[int, _LaneModel] = {}

    # ------------------------------------------------------------- targets

    @property
    def static_target(self) -> int:
        return self.config.executor_pod_queue_target_length

    def _initial_target(self) -> int:
        """A fresh lane starts at the static constant clamped into the
        dynamic bounds: at rest, enabled-and-idle behaves exactly like the
        static pool until demand (or the idle decay) says otherwise."""
        return min(max(self.static_target, self.min_target), self.max_target)

    def _lane(self, lane: int) -> _LaneModel:
        model = self._lanes.get(lane)
        if model is None:
            model = _LaneModel(self._initial_target())
            self._lanes[lane] = model
        return model

    def target(self, lane: int) -> int:
        """The lane's CURRENT warm-pool target (before the executor's
        physical-capacity clamp). Disabled, or a deployment that set the
        static target to 0 ("no warm pool"): the static constant, verbatim."""
        if not self.enabled or self.static_target <= 0:
            return self.static_target
        model = self._lanes.get(lane)
        return model.target if model is not None else self._initial_target()

    # -------------------------------------------------------------- inputs

    def observe_arrival(
        self, lane: int, snapshot: LaneSnapshot, *, jobs: int = 1
    ) -> None:
        """One acquisition arriving on the lane (a batched dispatch's
        multi-job token counts as its N coalesced requests). Updates the
        arrival-rate EWMA and applies scale-UP immediately, so the refill
        the arriving burst triggers already sees the raised target."""
        if not self.enabled or self.static_target <= 0:
            return
        model = self._lane(lane)
        now = self.clock()
        if model.last_arrival is not None:
            gap = max(now - model.last_arrival, 1e-3)
            sample = max(1, jobs) / gap
            if model.arrival_rate is None:
                model.arrival_rate = sample
            else:
                model.arrival_rate = (
                    self._alpha * sample + (1.0 - self._alpha) * model.arrival_rate
                )
        model.last_arrival = now
        # The arriving request is not in `queued` yet — count it.
        self._maybe_scale_up(lane, model, snapshot, now, extra=max(1, jobs))

    # ------------------------------------------------------------ the model

    def _effective_rate(self, model: _LaneModel, now: float) -> float:
        """The arrival-rate estimate, bounded by what the time since the
        last arrival can still justify: an EWMA frozen at burst height
        would otherwise keep spawn-ahead demand alive long after traffic
        stopped."""
        if model.arrival_rate is None or model.last_arrival is None:
            return 0.0
        idle = now - model.last_arrival
        if idle <= 0:
            return model.arrival_rate
        return min(model.arrival_rate, 1.0 / idle)

    def raw_demand(
        self,
        lane: int,
        snapshot: LaneSnapshot,
        *,
        now: float | None = None,
        extra: int = 0,
    ) -> float:
        """The lane's instantaneous demand in sandboxes: requests being
        served + requests waiting (+ the one arriving) + the spawn-ahead
        term (requests expected to arrive while one spawn completes) + the
        queue-wait pressure headroom.

        Spawn-ahead is weighted by the queue-wait evidence: a fast
        SEQUENTIAL client produces a sky-high arrival rate at concurrency
        one (each request departs before the next arrives — the
        instantaneous counts already cover it, and its grant waits sit at
        ~zero), so rate x spawn-time alone would over-provision every
        busy-but-not-contended lane. Scaled by wait_ewma/wait_target
        (capped at 1), the term only provisions ahead once recent waits
        show supply actually lagging arrivals — which is precisely the
        \"demand will outrun supply\" condition the ISSUE names."""
        model = self._lane(lane)
        if now is None:
            now = self.clock()
        wait_target = self.config.pool_target_queue_wait
        evidence = 1.0
        if wait_target > 0:
            evidence = min(1.0, snapshot.queue_wait_ewma / wait_target)
        spawn_ahead = (
            self._effective_rate(model, now)
            * max(0.0, snapshot.spawn_ewma)
            * evidence
        )
        raw = float(snapshot.in_use + snapshot.queued + extra) + spawn_ahead
        # Hibernated-wake term: each parked session whose wake lands here
        # contributes a configurable fraction of a warm sandbox. Off by
        # default (weight 0.0) — hibernated supply then stays silently
        # freed capacity, exactly the pre-signal behavior.
        wake_weight = float(
            getattr(self.config, "pool_hibernated_wake_weight", 0.0)
        )
        if wake_weight > 0 and snapshot.hibernated > 0:
            raw += wake_weight * snapshot.hibernated
        model.last_hibernated = snapshot.hibernated
        if (
            wait_target > 0
            and snapshot.queue_wait_ewma > wait_target
            and (snapshot.queued + snapshot.in_use + extra) > 0
        ):
            # Sustained waiting: supply has been lagging demand even when
            # the instantaneous counts look covered — add headroom
            # proportional to how far past acceptable the wait runs.
            raw += snapshot.queue_wait_ewma / wait_target
        model.last_raw = raw
        return raw

    @staticmethod
    def _whole(raw: float) -> int:
        """Demand in whole sandboxes, round-half-up: ceil would let a
        hair of spawn-ahead (raw 1.01) round a satisfied lane up a whole
        sandbox on every arrival — the fractional terms must accumulate
        to half a sandbox of real demand before they cost one."""
        return int(math.floor(raw + 0.5))

    def _maybe_scale_up(
        self,
        lane: int,
        model: _LaneModel,
        snapshot: LaneSnapshot,
        now: float,
        *,
        extra: int = 0,
    ) -> None:
        raw = self.raw_demand(lane, snapshot, now=now, extra=extra)
        desired = min(self._whole(raw), self.max_target)
        if desired > model.target:
            previous = model.target
            model.target = desired
            model.below_since = None
            model.scale_ups += 1
            self._record_event(lane, SCALE_UP, previous, desired, raw)
        elif raw >= model.target:
            model.below_since = None

    def evaluate(self, lane: int, snapshot: LaneSnapshot) -> int:
        """One sweep-cadence evaluation: scale up when demand outruns the
        target, otherwise run the hysteresis clock and step the target down
        once it expires. Returns the (possibly updated) target."""
        if not self.enabled or self.static_target <= 0:
            return self.static_target
        model = self._lane(lane)
        now = self.clock()
        raw = self.raw_demand(lane, snapshot, now=now)
        desired = min(self._whole(raw), self.max_target)
        if desired > model.target:
            previous = model.target
            model.target = desired
            model.below_since = None
            model.scale_ups += 1
            self._record_event(lane, SCALE_UP, previous, desired, raw)
            return model.target
        if desired >= model.target:
            model.below_since = None
            return model.target
        # Demand below target: hysteresis, then one step per evaluation —
        # gradual release, so a mid-decay burst only has to win back one
        # notch, not the whole ramp.
        if model.below_since is None:
            model.below_since = now
            return model.target
        if now - model.below_since < self.config.pool_scale_down_after:
            return model.target
        floor = max(desired, self.min_target)
        stepped = max(floor, model.target - 1)
        if stepped < model.target:
            previous = model.target
            model.target = stepped
            model.scale_downs += 1
            self._record_event(lane, SCALE_DOWN, previous, stepped, raw)
        return model.target

    # ---------------------------------------------------------- accounting

    def note_reaped(self, lane: int, count: int) -> None:
        """The executor's idle reaper disposed `count` excess warm
        sandboxes on the lane (bookkeeping + the reap scale-event)."""
        if count <= 0:
            return
        model = self._lane(lane)
        model.reaped += count
        events = getattr(self.metrics, "pool_scale_events", None)
        if events is not None:
            events.inc(count, chip_count=str(lane), direction=SCALE_REAP)

    def _record_event(
        self, lane: int, direction: str, previous: int, target: int, raw: float
    ) -> None:
        logger.info(
            "autoscale %s: lane-%d target %d -> %d (raw demand %.2f)",
            direction,
            lane,
            previous,
            target,
            raw,
        )
        events = getattr(self.metrics, "pool_scale_events", None)
        if events is not None:
            events.inc(chip_count=str(lane), direction=direction)
        if self.tracer is not None:
            # Scale decisions are rare and exactly what a capacity review
            # pulls up: record_span bypasses head sampling (fresh trace id,
            # zero-duration span — the device-health transition
            # discipline), retrievable via /traces at any sample ratio.
            self.tracer.record_span(
                "autoscale.transition",
                trace_id=tracing.new_trace_id(),
                parent_id=None,
                start_unix=time.time(),
                duration_s=0.0,
                attributes={
                    "lane": lane,
                    "direction": direction,
                    "from": previous,
                    "to": target,
                    "raw_demand": round(raw, 3),
                },
            )

    # ------------------------------------------------------------- surfaces

    def lanes(self) -> list[int]:
        return list(self._lanes)

    def snapshot(self) -> dict:
        """The /statusz autoscaler section: the model's verdicts next to
        the demand signals driving them."""
        body: dict = {
            "enabled": self.enabled,
            "min_target": self.min_target,
            "max_target": self.max_target,
            "static_target": self.static_target,
        }
        if not self.enabled:
            return body
        now = self.clock()
        body["lanes"] = {
            str(lane): {
                "target": model.target,
                "raw_demand": round(model.last_raw, 3),
                "hibernated": model.last_hibernated,
                "arrival_rate_per_s": round(
                    self._effective_rate(model, now), 3
                ),
                "scale_ups": model.scale_ups,
                "scale_downs": model.scale_downs,
                "reaped": model.reaped,
            }
            for lane, model in sorted(self._lanes.items())
        }
        return body
