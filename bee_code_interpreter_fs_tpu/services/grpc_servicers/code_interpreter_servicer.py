"""gRPC servicer for CodeInterpreterService.

Parity with the reference servicer (src/code_interpreter/services/
grpc_servicers/code_interpreter_servicer.py:40-136): per-request id into the
logging ContextVar, request validation → INVALID_ARGUMENT abort, domain
errors mapped into the response oneof error variants. Wired to the fixed
executor signature supporting both source_code and source_file (the reference
called `execute(source_code=...)` which its own executor no longer accepted —
SURVEY.md §0.1).
"""

from __future__ import annotations

import json
import logging

import grpc

from ...proto import code_interpreter_pb2 as pb2
from ...utils.logs import new_request_id
from ...utils.validation import OBJECT_ID_RE
from ..code_executor import (
    CircuitOpenError,
    CodeExecutor,
    ExecutorError,
    LimitExceededError,
    QuotaExceededError,
    SessionLimitError,
    SessionRestoringError,
    StaleLeaseError,
    StateStoreDegradedError,
)
from ..custom_tool_executor import (
    CustomToolExecuteError,
    CustomToolExecutor,
    CustomToolParseError,
)
from ..backends.base import SandboxSpawnError

logger = logging.getLogger(__name__)


class CodeInterpreterServicer:
    def __init__(
        self, code_executor: CodeExecutor, custom_tool_executor: CustomToolExecutor
    ) -> None:
        self.code_executor = code_executor
        self.custom_tool_executor = custom_tool_executor

    @property
    def tracer(self):
        return self.code_executor.tracer

    @staticmethod
    def _metadata_dict(context) -> dict:
        """Invocation metadata as a plain dict (first value wins)."""
        metadata: dict = {}
        metadata_fn = getattr(context, "invocation_metadata", None)
        invocation_metadata = metadata_fn() if metadata_fn is not None else None
        if invocation_metadata:
            # grpc.aio yields (key, value) tuples; the sync API yields
            # entries with .key/.value — accept both (tests fake either).
            for entry in invocation_metadata:
                key, value = (
                    (entry.key, entry.value)
                    if hasattr(entry, "key")
                    else (entry[0], entry[1])
                )
                metadata.setdefault(key, value)
        return metadata

    def _begin_rpc(
        self,
        context,
        *,
        trace_name: str | None = None,
        metadata: dict | None = None,
    ) -> tuple[str, object, list[tuple[str, str]]]:
        """Per-RPC correlation: a fresh request id (logging ContextVar) and,
        for executing RPCs, a root trace span joined from `x-traceparent`
        metadata (the transport-level analogue of the HTTP `traceparent`
        header). Both ids are echoed in TRAILING metadata (`x-request-id` /
        `x-trace-id`) — before this PR the gRPC request id existed only in
        logs. Trailing (not initial) metadata so streaming RPCs carry it
        too, and because it survives context.abort(). The trailing list is
        returned so error paths (e.g. `x-violation`) can extend it without
        losing the correlation ids."""
        request_id = new_request_id()
        span = None
        if trace_name is not None:
            metadata = metadata if metadata is not None else {}
            span = self.tracer.start_trace(
                trace_name,
                traceparent=metadata.get("x-traceparent")
                or metadata.get("traceparent"),
                attributes={"request_id": request_id},
            )
        trailing = [("x-request-id", request_id)]
        if span is not None and span.trace_id:
            trailing.append(("x-trace-id", span.trace_id))
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(trailing))
        return request_id, span, trailing

    @staticmethod
    async def _admission_from_metadata(
        context: grpc.aio.ServicerContext,
        metadata: dict | None = None,
    ) -> dict:
        """Tenant/priority/deadline for the fair-share scheduler, carried as
        gRPC invocation metadata (`x-tenant`, `x-priority`,
        `x-deadline-seconds`) — the transport-level analogue of the HTTP
        surface's X-Tenant / X-Priority / X-Deadline-Seconds headers, so a
        gateway can tag requests without touching the message. Value
        validation (tenant charset, priority names) lives in the scheduler;
        its ValueError maps to INVALID_ARGUMENT on the shared path."""
        if metadata is None:
            metadata = CodeInterpreterServicer._metadata_dict(context)
        deadline = None
        raw = metadata.get("x-deadline-seconds")
        if raw is not None:
            try:
                deadline = float(raw)
            except ValueError:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "x-deadline-seconds metadata must be a number",
                )
        return {
            "tenant": metadata.get("x-tenant"),
            "priority": metadata.get("x-priority"),
            "deadline": deadline,
        }

    @staticmethod
    def _pure_from_metadata(metadata: dict) -> bool:
        """Purity declaration for the result-memo path, carried as `x-pure`
        invocation metadata — the transport-level analogue of the HTTP
        surface's `pure` request field (the proto is frozen, so the flag
        rides metadata like tenant/priority/limits do). Opt-in: anything
        but an explicit true-ish value means the default, un-memoized
        path."""
        raw = metadata.get("x-pure")
        if raw is None:
            return False
        return str(raw).strip().lower() in ("1", "true", "yes", "on")

    @staticmethod
    async def _limits_from_metadata(
        context: grpc.aio.ServicerContext, metadata: dict
    ) -> dict | None:
        """Per-request resource-budget override as `x-sandbox-limits`
        metadata (a JSON object) — the transport-level analogue of the HTTP
        X-Sandbox-Limits header; the proto is frozen (no codegen in this
        environment), so the budget rides metadata like tenant/priority do.
        Key/value validation lives in services.limits (ValueError →
        INVALID_ARGUMENT on the shared path)."""
        raw = metadata.get("x-sandbox-limits")
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "x-sandbox-limits metadata must be a JSON object",
            )

    @staticmethod
    def _attach_usage_trailing(
        context: grpc.aio.ServicerContext,
        trailing: list[tuple[str, str]],
        result,
    ) -> None:
        """Per-request usage attribution on the wire: the proto is frozen
        (no protoc in the image), so the billed chip-seconds /
        device-op-seconds ride trailing metadata — the same structured
        channel x-violation uses. Absent with the metering kill switch off
        (the phases fields don't exist then): pre-metering trailing
        metadata, byte-for-byte."""
        chip = result.phases.get("chip_seconds")
        device = result.phases.get("device_op_seconds")
        quota = result.phases.get("quota")
        memo = result.phases.get("memo")
        if (
            not isinstance(chip, (int, float))
            and not isinstance(device, (int, float))
            and not isinstance(quota, dict)
            and not isinstance(memo, dict)
        ):
            return
        extra = list(trailing)
        if isinstance(memo, dict) and isinstance(memo.get("state"), str):
            # Result-memo disposition (hit|miss|bypass) — the transport
            # analogue of the HTTP X-Memo header. Absent entirely for
            # non-pure requests and with the memo kill switch off.
            extra.append(("x-memo", memo["state"]))
        if isinstance(chip, (int, float)):
            extra.append(("x-usage-chip-seconds", f"{float(chip):.6f}"))
        if isinstance(device, (int, float)):
            extra.append(
                ("x-usage-device-op-seconds", f"{float(device):.6f}")
            )
        if isinstance(quota, dict):
            # The pacing satellite, wire half: the remaining budget rides
            # the SUCCESS path too, so a well-behaved agent can slow down
            # before ever meeting RESOURCE_EXHAUSTED. Same structured
            # channel as x-usage-* (the proto is frozen).
            remaining = quota.get("remaining_chip_seconds")
            if isinstance(remaining, (int, float)):
                extra.append(
                    (
                        "x-quota-remaining-chip-seconds",
                        f"{float(remaining):.6f}",
                    )
                )
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(extra))

    @staticmethod
    async def _abort_violation(
        context: grpc.aio.ServicerContext,
        e: LimitExceededError,
        trailing: list[tuple[str, str]],
    ) -> None:
        """Typed limit violations map to RESOURCE_EXHAUSTED with the kind
        both in the message and as `x-violation` trailing metadata (the
        proto is frozen; metadata is the structured channel). Deterministic
        — never blind-retry."""
        trailing = trailing + [("x-violation", e.kind)]
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(trailing))
        await context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            f"sandbox resource limit exceeded [violation={e.kind}]: {e}",
        )

    @staticmethod
    async def _abort_restoring(
        context: grpc.aio.ServicerContext,
        e: SessionRestoringError,
        trailing: list[tuple[str, str]],
    ) -> None:
        """Restore-in-flight refusals map to UNAVAILABLE — transient by
        construction, the restore completes without the loser — with
        `x-session-restoring` trailing metadata carrying the retry-after
        (the proto is frozen; metadata is the structured channel, as for
        x-violation and x-quota-*)."""
        extra = trailing + [
            ("x-session-restoring", "1"),
            (
                "x-session-restoring-retry-after",
                f"{max(0.0, getattr(e, 'retry_after', 1.0)):.3f}",
            ),
        ]
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(extra))
        await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    @staticmethod
    async def _abort_store_degraded(
        context: grpc.aio.ServicerContext,
        e: StateStoreDegradedError,
        trailing: list[tuple[str, str]],
    ) -> None:
        """Fail-closed store-outage refusals (lease mint, session restore)
        map to UNAVAILABLE — transient; the store heals and the retry
        succeeds — with `x-store-degraded` trailing metadata carrying the
        subsystem and retry-after (the proto is frozen; metadata is the
        structured channel, as for x-session-restoring)."""
        extra = trailing + [
            ("x-store-degraded", getattr(e, "subsystem", "") or "1"),
            (
                "x-store-degraded-retry-after",
                f"{max(0.0, getattr(e, 'retry_after', 5.0) or 5.0):.3f}",
            ),
        ]
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(extra))
        await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    @staticmethod
    async def _abort_quota(
        context: grpc.aio.ServicerContext,
        e: QuotaExceededError,
        trailing: list[tuple[str, str]],
    ) -> None:
        """Quota denials map to RESOURCE_EXHAUSTED — the same retryable
        family as every capacity shed — with `x-quota-*` trailing metadata
        carrying the typed reason, the window-derived retry-after, and the
        remaining budget (the proto is frozen; metadata is the structured
        channel, as for x-violation and x-usage-*)."""
        extra = trailing + [
            ("x-quota-reason", e.reason),
            ("x-quota-retry-after", f"{max(0.0, e.retry_after):.3f}"),
        ]
        if e.remaining_chip_seconds is not None:
            extra.append(
                (
                    "x-quota-remaining-chip-seconds",
                    f"{e.remaining_chip_seconds:.6f}",
                )
            )
        if e.limit_chip_seconds is not None:
            extra.append(
                ("x-quota-limit-chip-seconds", f"{e.limit_chip_seconds:.6f}")
            )
        if e.window_seconds is not None:
            extra.append(
                ("x-quota-window-seconds", f"{e.window_seconds:.3f}")
            )
        if getattr(e, "remaining_hbm_byte_seconds", None) is not None:
            extra.append(
                (
                    "x-quota-remaining-hbm-byte-seconds",
                    f"{e.remaining_hbm_byte_seconds:.3f}",
                )
            )
        if getattr(e, "limit_hbm_byte_seconds", None) is not None:
            extra.append(
                (
                    "x-quota-limit-hbm-byte-seconds",
                    f"{e.limit_hbm_byte_seconds:.3f}",
                )
            )
        if getattr(e, "burst_credits_remaining", None) is not None:
            extra.append(
                ("x-quota-burst-credits", f"{e.burst_credits_remaining:.6f}")
            )
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(extra))
        await context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            f"quota denied [reason={e.reason}]: {e}",
        )

    @staticmethod
    async def _validate_execute_request(
        request: pb2.ExecuteRequest, context: grpc.aio.ServicerContext
    ) -> tuple[bool, bool]:
        """Shared Execute/ExecuteStream request validation; returns
        (has_code, has_file) or aborts with INVALID_ARGUMENT."""
        has_code = bool(request.source_code)
        has_file = bool(request.source_file)
        if has_code == has_file:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "exactly one of source_code/source_file is required",
            )
        if request.timeout < 0:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "timeout must be >= 0"
            )
        if request.chip_count < 0:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "chip_count must be >= 0"
            )
        for path, object_id in request.files.items():
            if not OBJECT_ID_RE.match(object_id):
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"invalid file object id for {path}",
                )
        return has_code, has_file

    async def _check_session_owner(
        self,
        context: grpc.aio.ServicerContext,
        executor_id: str | None,
        metadata: dict,
        trailing: list[tuple[str, str]] | None = None,
    ) -> None:
        """Session→replica affinity on the gRPC edge: a session request
        this replica does not own aborts UNAVAILABLE with the owner's
        identity (and address, when known) in trailing metadata
        (`x-replica-owner` / `x-replica-owner-url`) — the transport-level
        analogue of the HTTP 307 + X-Replica-Owner contract (gRPC has no
        transparent-proxy story without a full client channel per peer;
        clients re-resolve against the named owner). Stateless RPCs and
        single-replica mode pass through untouched."""
        router = self.code_executor.session_router
        if router is None or not executor_id:
            return
        tenant = metadata.get("x-tenant")
        if router.owns(tenant, executor_id):
            return
        owner = router.owner_of(tenant, executor_id)
        extra = list(trailing or []) + [("x-replica-owner", owner)]
        url = router.ring.url_of(owner)
        if url:
            extra.append(("x-replica-owner-url", url))
        set_trailing = getattr(context, "set_trailing_metadata", None)
        if set_trailing is not None:
            set_trailing(tuple(extra))
        await context.abort(
            grpc.StatusCode.UNAVAILABLE,
            f"session {executor_id!r} is owned by replica {owner!r}; "
            "re-issue against it (x-replica-owner metadata)",
        )

    @staticmethod
    def _result_to_response(result) -> pb2.ExecuteResponse:
        response = pb2.ExecuteResponse(
            stdout=result.stdout,
            stderr=result.stderr,
            exit_code=result.exit_code,
            session_seq=result.session_seq,
            session_ended=result.session_ended,
            stdout_truncated=result.stdout_truncated,
            stderr_truncated=result.stderr_truncated,
        )
        for path, object_id in result.files.items():
            response.files[path] = object_id
        return response

    async def Execute(
        self, request: pb2.ExecuteRequest, context: grpc.aio.ServicerContext
    ) -> pb2.ExecuteResponse:
        metadata = self._metadata_dict(context)
        request_id, span, trailing = self._begin_rpc(
            context, trace_name="grpc Execute", metadata=metadata
        )
        logger.info("Execute [%s] chip_count=%d", request_id, request.chip_count)
        with span:
            has_code, has_file = await self._validate_execute_request(
                request, context
            )
            await self._check_session_owner(
                context, request.executor_id or None, metadata, trailing
            )
            admission = await self._admission_from_metadata(context, metadata)
            limits = await self._limits_from_metadata(context, metadata)
            # executor_id pattern validation lives in the executor (its
            # ValueError maps to INVALID_ARGUMENT below, same as the HTTP
            # path).
            try:
                result = await self.code_executor.execute(
                    request.source_code if has_code else None,
                    source_file=request.source_file if has_file else None,
                    files=dict(request.files),
                    timeout=request.timeout or None,
                    env=dict(request.env) or None,
                    chip_count=request.chip_count or None,
                    profile=request.profile,
                    executor_id=request.executor_id or None,
                    limits=limits,
                    pure=self._pure_from_metadata(metadata),
                    **admission,
                )
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except LimitExceededError as e:
                await self._abort_violation(context, e, trailing)
            except QuotaExceededError as e:
                # Before SessionLimitError (it subclasses it): the typed
                # quota denial with x-quota-* trailing metadata.
                await self._abort_quota(context, e, trailing)
            except CircuitOpenError as e:
                # Degraded mode (spawn circuit open): UNAVAILABLE, mirroring
                # the HTTP layer's 503 shed — the health service reports
                # NOT_SERVING over the same window. Distinct from
                # RESOURCE_EXHAUSTED below, which means the service is
                # healthy but capacity-capped.
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except SessionLimitError as e:
                # Retryable resource exhaustion, not a defect in the request.
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except SessionRestoringError as e:
                # Before ExecutorError (its parent): a concurrent turn owns
                # the session's restore — UNAVAILABLE with
                # x-session-restoring metadata, mirroring the HTTP 409.
                await self._abort_restoring(context, e, trailing)
            except StaleLeaseError as e:
                # Before ExecutorError (its parent): the request's host was
                # fenced mid-flight — ABORTED is gRPC's "safe to retry the
                # whole transaction" signal, mirroring the HTTP 409.
                await context.abort(grpc.StatusCode.ABORTED, str(e))
            except StateStoreDegradedError as e:
                # Before ExecutorError: fail-closed store outage —
                # UNAVAILABLE with x-store-degraded metadata, mirroring
                # the HTTP 503 + Retry-After.
                await self._abort_store_degraded(context, e, trailing)
            except (ExecutorError, SandboxSpawnError) as e:
                logger.exception("Execute failed [%s]", request_id)
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            self._attach_usage_trailing(context, trailing, result)
            return self._result_to_response(result)

    async def ExecuteStream(
        self, request: pb2.ExecuteRequest, context: grpc.aio.ServicerContext
    ):
        """Server-streaming Execute: OutputChunk events while the code runs,
        then one `result` event (identical to Execute's response)."""
        metadata = self._metadata_dict(context)
        request_id, span, trailing = self._begin_rpc(
            context, trace_name="grpc ExecuteStream", metadata=metadata
        )
        logger.info(
            "ExecuteStream [%s] chip_count=%d", request_id, request.chip_count
        )
        with span:
            has_code, has_file = await self._validate_execute_request(
                request, context
            )
            await self._check_session_owner(
                context, request.executor_id or None, metadata, trailing
            )
            admission = await self._admission_from_metadata(context, metadata)
            limits = await self._limits_from_metadata(context, metadata)
            events = self.code_executor.execute_stream(
                request.source_code if has_code else None,
                source_file=request.source_file if has_file else None,
                files=dict(request.files),
                timeout=request.timeout or None,
                env=dict(request.env) or None,
                chip_count=request.chip_count or None,
                profile=request.profile,
                executor_id=request.executor_id or None,
                limits=limits,
                pure=self._pure_from_metadata(metadata),
                **admission,
            )
            try:
                async for event in events:
                    if "result" in event:
                        self._attach_usage_trailing(
                            context, trailing, event["result"]
                        )
                        yield pb2.ExecuteStreamEvent(
                            result=self._result_to_response(event["result"])
                        )
                    else:
                        yield pb2.ExecuteStreamEvent(
                            chunk=pb2.ExecuteStreamEvent.OutputChunk(
                                stream=event.get("stream", ""),
                                data=event.get("data", ""),
                            )
                        )
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except LimitExceededError as e:
                await self._abort_violation(context, e, trailing)
            except QuotaExceededError as e:
                await self._abort_quota(context, e, trailing)
            except CircuitOpenError as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except SessionLimitError as e:
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except SessionRestoringError as e:
                # Restore-in-flight: UNAVAILABLE + x-session-restoring, like
                # Execute's mapping above.
                await self._abort_restoring(context, e, trailing)
            except StaleLeaseError as e:
                # Fenced mid-stream: ABORTED (retry-whole-call), like
                # Execute's mapping above.
                await context.abort(grpc.StatusCode.ABORTED, str(e))
            except StateStoreDegradedError as e:
                # Fail-closed store outage: UNAVAILABLE + x-store-degraded,
                # like Execute's mapping above.
                await self._abort_store_degraded(context, e, trailing)
            except (ExecutorError, SandboxSpawnError) as e:
                logger.exception("ExecuteStream failed [%s]", request_id)
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def CloseExecutor(
        self, request: pb2.CloseExecutorRequest, context: grpc.aio.ServicerContext
    ) -> pb2.CloseExecutorResponse:
        self._begin_rpc(context)
        if not OBJECT_ID_RE.match(request.executor_id):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "invalid executor_id (want ^[0-9a-zA-Z_-]{1,255}$)",
            )
        metadata = self._metadata_dict(context)
        await self._check_session_owner(
            context, request.executor_id, metadata
        )
        closed = await self.code_executor.close_session(
            request.executor_id, tenant=metadata.get("x-tenant")
        )
        return pb2.CloseExecutorResponse(closed=closed)

    async def ParseCustomTool(
        self, request: pb2.ParseCustomToolRequest, context: grpc.aio.ServicerContext
    ) -> pb2.ParseCustomToolResponse:
        self._begin_rpc(context)
        try:
            tool = self.custom_tool_executor.parse(request.tool_source_code)
        except CustomToolParseError as e:
            return pb2.ParseCustomToolResponse(
                error=pb2.ParseCustomToolResponse.Error(error_messages=e.errors)
            )
        return pb2.ParseCustomToolResponse(
            success=pb2.ParseCustomToolResponse.Success(
                tool_name=tool.name,
                tool_input_schema_json=json.dumps(tool.input_schema),
                tool_description=tool.description,
            )
        )

    async def ExecuteCustomTool(
        self, request: pb2.ExecuteCustomToolRequest, context: grpc.aio.ServicerContext
    ) -> pb2.ExecuteCustomToolResponse:
        metadata = self._metadata_dict(context)
        request_id, span, trailing = self._begin_rpc(
            context, trace_name="grpc ExecuteCustomTool", metadata=metadata
        )
        with span:
            if request.timeout < 0:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "timeout must be >= 0"
                )
            await self._check_session_owner(
                context, request.executor_id or None, metadata, trailing
            )
            try:
                tool_input = json.loads(request.tool_input_json)
            except json.JSONDecodeError:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "tool_input_json is not valid JSON",
                )
            try:
                output, exec_result = (
                    await self.custom_tool_executor.execute_with_result(
                        request.tool_source_code,
                        tool_input,
                        executor_id=request.executor_id or None,
                        timeout=request.timeout or None,
                    )
                )
            except CustomToolParseError as e:
                return pb2.ExecuteCustomToolResponse(
                    error=pb2.ExecuteCustomToolResponse.Error(
                        stderr="\n".join(e.errors)
                    )
                )
            except CustomToolExecuteError as e:
                # Continuity on failure too (see proto Error comment).
                return pb2.ExecuteCustomToolResponse(
                    error=pb2.ExecuteCustomToolResponse.Error(
                        stderr=e.stderr,
                        session_seq=e.result.session_seq if e.result else 0,
                        session_ended=e.result.session_ended if e.result else False,
                    )
                )
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except LimitExceededError as e:
                await self._abort_violation(context, e, trailing)
            except QuotaExceededError as e:
                await self._abort_quota(context, e, trailing)
            except CircuitOpenError as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except SessionLimitError as e:
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except StateStoreDegradedError as e:
                # Fail-closed store outage: UNAVAILABLE + x-store-degraded,
                # like Execute's mapping above.
                await self._abort_store_degraded(context, e, trailing)
            except (ExecutorError, SandboxSpawnError) as e:
                logger.exception("ExecuteCustomTool failed [%s]", request_id)
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            return pb2.ExecuteCustomToolResponse(
                success=pb2.ExecuteCustomToolResponse.Success(
                    tool_output_json=json.dumps(output),
                    session_seq=exec_result.session_seq,
                    session_ended=exec_result.session_ended,
                )
            )

    def method_handlers(self) -> dict[str, grpc.RpcMethodHandler]:
        return {
            "Execute": grpc.unary_unary_rpc_method_handler(
                self.Execute,
                request_deserializer=pb2.ExecuteRequest.FromString,
                response_serializer=pb2.ExecuteResponse.SerializeToString,
            ),
            "ParseCustomTool": grpc.unary_unary_rpc_method_handler(
                self.ParseCustomTool,
                request_deserializer=pb2.ParseCustomToolRequest.FromString,
                response_serializer=pb2.ParseCustomToolResponse.SerializeToString,
            ),
            "ExecuteCustomTool": grpc.unary_unary_rpc_method_handler(
                self.ExecuteCustomTool,
                request_deserializer=pb2.ExecuteCustomToolRequest.FromString,
                response_serializer=pb2.ExecuteCustomToolResponse.SerializeToString,
            ),
            "ExecuteStream": grpc.unary_stream_rpc_method_handler(
                self.ExecuteStream,
                request_deserializer=pb2.ExecuteRequest.FromString,
                response_serializer=pb2.ExecuteStreamEvent.SerializeToString,
            ),
            "CloseExecutor": grpc.unary_unary_rpc_method_handler(
                self.CloseExecutor,
                request_deserializer=pb2.CloseExecutorRequest.FromString,
                response_serializer=pb2.CloseExecutorResponse.SerializeToString,
            ),
        }
