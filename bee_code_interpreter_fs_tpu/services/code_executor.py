"""The orchestrator: pooled single-use sandboxes + file round-trips.

Behavior parity with the reference's KubernetesCodeExecutor
(src/code_interpreter/services/kubernetes_code_executor.py:48-279), rebuilt
backend-agnostic and TPU-aware:

- `execute()` accepts BOTH inline `source_code` and file-based `source_file`
  coherently (the reference fork broke mid-refactor and its gRPC path crashed
  on the old kwarg — SURVEY.md §0.1; here both surfaces work).
- Warm pool is keyed by chip_count lanes: an Execute asking for a 4-chip
  slice gets a sandbox whose warm runner already initialized that topology
  (kubernetes_code_executor.py:163-201 pooled only "a pod"; a TPU pool must
  pool "a topology" — SURVEY.md §2 census).
- Workspace sync is delta-based (services/transfer.py): per-host SHA-256
  manifests skip uploads the sandbox already holds and downloads whose
  content is already in content-addressed Storage — a session turn with
  unchanged input files moves O(1) bytes, not O(total bytes x hosts). Hosts
  on an old executor binary transparently fall back to full transfers.
- Infrastructure failures retry up to 3× with exponential backoff
  (kubernetes_code_executor.py:76-80); user-code failures never retry.
- Per-request phase timings (queue-wait/upload/exec/download) are returned —
  the observability the reference lacked (SURVEY.md §5).
"""

from __future__ import annotations

import asyncio
import contextvars
import json
import logging
import time
from collections import deque
from dataclasses import dataclass, field

import httpx

from ..config import Config
from ..utils import tracing
from ..utils.logs import PhaseTimer
from ..utils.metrics import ExecutorMetrics
from ..utils.retrying import RetryPolicy, retry_async
from ..utils.tracing import Tracer
from ..utils.validation import (
    OBJECT_ID_RE,
    SHA256_HEX_RE,
    normalize_workspace_path,
)
from .autoscaler import LaneSnapshot, PoolAutoscaler
from .backends.base import Sandbox, SandboxBackend, SandboxSpawnError, num_hosts_for
from .batcher import Batcher, BatchJob, BatchKey, freeze_mapping
from .circuit_breaker import BreakerBoard
from .compile_cache import (
    PREWARM_SOURCES,
    CompileCacheStore,
    SandboxCacheSync,
)
from .errors import (  # noqa: F401 — canonical home is errors.py; re-exported
    AdmissionRejectedError,
    CapacityTimeoutError,
    CircuitOpenError,
    DeadlineInfeasibleError,
    ExecutorError,
    LimitExceededError,
    QueueDepthError,
    QuotaExceededError,
    SessionLimitError,
    SessionRestoringError,
    StaleLeaseError,
    StateStoreDegradedError,
)
from .leases import Lease, LeaseRegistry
from .limits import VIOLATION_KINDS, request_limits, validate_config_limits
from .perf_observer import PerfObserver
from .quotas import QuotaEnforcer, QuotaVerdict
from .result_memo import (
    SHARED_SCOPE,
    ResultMemoStore,
    binary_key_of,
    derive_key,
    result_content_sha,
)
from .scheduler import SandboxScheduler
from .session_store import SessionStore
from .state_store import StateStore, make_state_store, resolve_replica_id
from .storage import Storage, StorageObjectNotFound
from .transfer import (
    HostManifest,
    SandboxTransfer,
    TransferStats,
    parse_files_field,
)
from .usage import UsageDraft, UsageLedger

logger = logging.getLogger(__name__)

# The ONLY Result.phases keys the phase_seconds latency histogram may
# observe. Structural fix for a bug class three PRs re-fixed one key at a
# time (compile_cache_* in PR 6, batch_jobs/batch_index in PR 7, again in
# PR 8): phases also carries byte counts, cache/demux coordinates, the
# trace id, and now per-tenant attribution fields (chip_seconds /
# device_op_seconds) — none of which are latencies. An ALLOWLIST means a
# new non-latency key is excluded by default instead of poisoning the
# histogram until someone notices; a new latency phase must be added here
# deliberately (and the regression test in test_usage.py will catch a
# histogram observing anything else).
LATENCY_PHASES = frozenset(
    {"queue_wait", "upload", "exec", "download", "restore"}
)

# True only inside _execute_trusted (the compile-cache pre-warm): the running
# request's source is control-plane-authored, so it does NOT taint its
# sandbox's compile-cache provenance. Everything else — every API-originated
# execute, session or one-shot — is tenant code and taints the sandbox
# forever (see SandboxCacheSync.tainted). A contextvar, not a parameter:
# the flag must ride the request's own task through the retry/session
# plumbing without widening every signature in between.
_trusted_source_var: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "compile_cache_trusted_source", default=False
)

# The trigger reason when THIS request's profiler run was armed by the perf
# observer (auto-triggered profiling), None otherwise. Control-plane-induced
# work must not hit tenant ledgers (the PR 9 trusted-run rule): the harvest
# path reads this to pull profile.zip OUT of the tenant's files/bill and
# into the profile store. A contextvar for the same reason as
# _trusted_source_var: the flag must ride the request's own task through
# the session/stream plumbing without widening every signature in between.
_auto_profile_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "perf_auto_profile_reason", default=None
)

# True while the running request DECLARED purity (no net, no randomness, no
# wall-clock reads — the client's promise): _run_on_sandbox forwards the
# declaration to the executor, which echoes it with a hashed result block
# the memo-record path verifies end-to-end. A contextvar for the same
# reason as the two above: the flag must ride the request's own task
# through retry/batch/stream plumbing without widening every signature.
_pure_run_var: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "result_memo_pure_run", default=False
)


def _drain(pool: deque) -> list:
    drained = []
    while pool:
        drained.append(pool.popleft())
    return drained


@dataclass
class Result:
    stdout: str
    stderr: str
    exit_code: int
    files: dict[str, str]  # absolute workspace path -> storage object id
    # Phase timings (seconds) + transfer byte counters, plus the request's
    # trace_id (a string) when tracing sampled it.
    phases: dict[str, float | str] = field(default_factory=dict)
    warm: bool = False
    # Per-stream truncation markers (satellite: the executor always tracked
    # these; clients previously had to pattern-match "[stdout truncated]").
    stdout_truncated: bool = False
    stderr_truncated: bool = False
    # Session continuity (executor_id requests only; 0/False otherwise):
    # session_seq is this request's 1-based position in its session — a
    # client expecting an existing session that sees 1 knows prior state was
    # lost (idle expiry). session_ended reports that THIS request killed the
    # session (runner timeout-kill/crash); the next request starts fresh.
    session_seq: int = 0
    session_ended: bool = False
    # Executor-verified purity echo (declared-pure memo-miss runs only):
    # the result hash the executor computed over its response, re-derived
    # and matched by the control plane from the same wire fields. None when
    # the run didn't declare purity, an old binary didn't echo, or the
    # hashes disagreed — nothing is recorded then (services/result_memo.py).
    pure_echo: str | None = None


@dataclass
class _Session:
    """One executor_id's live sandbox lease.

    The sandbox is held OUT of the pool for the session's lifetime — no
    /reset between its requests, so the workspace (and the warm process's
    imported modules) persist. `lock` serializes requests sharing the id;
    `ready` lets concurrent first requests wait for one creation instead of
    racing spawns. A closed session stays closed — holders re-fetch from
    the session table and recreate."""

    lane: int
    sandbox: Sandbox | None = None
    ready: asyncio.Future = field(default_factory=asyncio.Future)
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    last_used: float = 0.0
    closed: bool = False
    seq: int = 0  # requests served (exposed as Result.session_seq)
    # Session durability plane (services/session_store.py): the tenant the
    # session was opened under (checkpoint key scope), the durable record
    # awaiting lazy restore on the first turn after a wake (None once
    # applied), whether that restore is in flight RIGHT NOW (a second turn
    # then sheds with the typed 409 instead of racing a double-restore),
    # and the sweep's idle-chip-seconds accounting watermark.
    tenant: str | None = None
    pending_restore: dict | None = None
    restoring: bool = False
    idle_accounted: float = 0.0


class CodeExecutor:
    def __init__(
        self,
        backend: SandboxBackend,
        storage: Storage,
        config: Config | None = None,
        metrics: ExecutorMetrics | None = None,
        breakers: BreakerBoard | None = None,
        scheduler: SandboxScheduler | None = None,
        tracer: Tracer | None = None,
        compile_cache: CompileCacheStore | None = None,
        usage: UsageLedger | None = None,
        quotas: QuotaEnforcer | None = None,
        perf: PerfObserver | None = None,
        state_store: StateStore | None = None,
    ) -> None:
        self.backend = backend
        self.storage = storage
        self.config = config or Config()
        # Malformed operator limit config must fail HERE (service boot),
        # not per request as a spurious client 400.
        validate_config_limits(self.config)
        self.metrics = metrics or ExecutorMetrics()
        # Pluggable control-plane state (services/state_store.py): the
        # scheduler's WFQ tags, breaker verdicts, lease generations/fence
        # floors, and lane-occupancy gauges route through this seam. The
        # default is a PRIVATE in-memory store — every component then
        # skips its cross-replica path and runs today's single-process
        # behavior byte-for-byte. A SHARED store (APP_STATE_STORE=sqlite
        # path, or one in-memory instance handed to several in-process
        # executors) is what lets N replicas cooperate instead of
        # double-granting lanes or double-fencing hosts.
        self.state_store = state_store or make_state_store(self.config)
        self._store_shared = bool(self.state_store.shared)
        self.replica_id = (
            resolve_replica_id(self.config) or self.config.replica_self or ""
        )
        if self._store_shared and not self.replica_id:
            # A shared store handed in directly (tests, the bench) still
            # needs a distinct identity per executor instance.
            self.replica_id = f"replica-{id(self) & 0xFFFF:04x}"
        # Session→replica affinity router (services/replicas.py), attached
        # by the application context when a replica set is configured;
        # surfaced through /statusz. None in single-replica mode.
        self.session_router = None
        # Short-lived cache over the peer-occupancy store scan (the
        # breaker's remote-read discipline): lane -> (expires_wall, busy).
        self._peer_busy_cache: dict[int, tuple[float, int]] = {}
        # Request-scoped tracing: the executor owns the tracer so both API
        # servers (which create the root spans) and the pipeline stages here
        # (which create children) share one sampling decision and one ring.
        self.tracer = tracer or Tracer.from_config(self.config, metrics=self.metrics)
        # Per-lane spawn circuit breakers: fail fast (retryable) while the
        # backend is persistently failing instead of burning each request's
        # 300s acquire budget plus a full retry ladder (injectable for
        # deterministic chaos tests).
        self.breakers = breakers or BreakerBoard(
            failure_threshold=self.config.breaker_failure_threshold,
            cooldown=self.config.breaker_cooldown,
            store=self.state_store,
        )
        # Backends with long-running watch paths (kubernetes pod-watch) feed
        # the same lane breakers directly, so a watch failure counts without
        # waiting for the whole spawn ladder to surface it.
        bind_breakers = getattr(self.backend, "bind_breakers", None)
        if bind_breakers is not None:
            bind_breakers(self.breakers)
        # All sandbox-slot admission goes through the fair-share scheduler:
        # per-lane ordered queues, weighted fair queueing across tenants,
        # priority classes, deadline-aware admission, bounded per-tenant
        # depth. _acquire is a thin client of its grant tokens.
        self.scheduler = scheduler or SandboxScheduler(
            self.config, metrics=self.metrics, store=self.state_store
        )
        # Per-tenant usage metering (services/usage.py): every request's
        # chip-seconds, queue wait, transfer bytes, recompiles, violations,
        # and request/batch-job counts attributed to its tenant, in a
        # durable journal-backed ledger. The kill switch constructs a
        # disabled ledger whose record paths are no-ops (pre-metering
        # behavior byte-for-byte). Queue wait is attributed by the
        # scheduler at grant time — only it knows tenant AND true wait.
        self.usage = usage or UsageLedger(self.config, metrics=self.metrics)
        if self.usage.enabled:
            self.scheduler.usage = self.usage
        # Quota enforcement (services/quotas.py): the admission gate that
        # READS the ledger above — sliding-window chip-second budgets,
        # request-rate/concurrency caps, and repeat-offender quarantine,
        # all checked before the scheduler ever enqueues. The kill switch
        # (APP_QUOTAS_ENABLED=0) constructs a disabled enforcer whose
        # admit()/release() are no-ops — pre-quota behavior byte-for-byte.
        self.quotas = quotas or QuotaEnforcer(
            self.config,
            usage=self.usage,
            metrics=self.metrics,
            store=self.state_store,
        )
        # Spawn retries mirror the reference's ladder (3 attempts, 0.5s
        # exponential base capped at 5s) with full jitter so parallel refill
        # failures don't re-synchronize into retry waves.
        self._spawn_retry_policy = RetryPolicy(
            attempts=max(1, self.config.executor_spawn_retry_attempts),
            base_delay=0.5,
            max_delay=5.0,
            retry_on=(SandboxSpawnError,),
        )
        self._execute_retry_policy = RetryPolicy(
            attempts=3,
            base_delay=0.5,
            max_delay=5.0,
            retry_on=(ExecutorError,),
        )
        self._pools: dict[int, deque[Sandbox]] = {}
        self._spawning: dict[int, int] = {}
        # Requests currently holding a sandbox, per lane. With reuse on,
        # these sandboxes come BACK to the pool at release (generation
        # turnover keeps the TPU lease), so they count toward the lane
        # target — a refill spawn for a sandbox that is about to recycle
        # would fight it for the physical TPU slot and lose (VERDICT r2 #1).
        self._in_use: dict[int, int] = {}
        # Of the in-use counts above, how many are only mid-RELEASE
        # (post-request turnover in a background task): still physical
        # slot-holders for the capacity math, but their requester is gone
        # — the autoscaler's demand model must not read them as load, or
        # a strictly sequential client (next request arriving while the
        # previous release settles) would ratchet the lane target up.
        self._releasing: dict[int, int] = {}
        # executor_id -> live session (sandbox held out of the pool).
        self._sessions: dict[str, _Session] = {}
        # EVERY live sandbox (pooled, in-use, session-parked), keyed by id:
        # the device-health probe's host inventory. Registered the moment a
        # spawn succeeds, dropped in _dispose — the in-use window is where
        # wedges actually happen (a mid-device-op kill), so probing only
        # the pool would miss the exact hosts that matter.
        self._live_sandboxes: dict[str, tuple[int, Sandbox]] = {}
        # Sandboxes held by sessions, per lane: they occupy physical TPU
        # slots (capacity accounting) but are NOT due back soon, so they are
        # tracked apart from _in_use (which waiters treat as imminent supply).
        self._session_held: dict[int, int] = {}
        self._fill_tasks: set[asyncio.Task] = set()
        self._dispose_tasks: set[asyncio.Task] = set()
        self._closed = False
        # Graceful drain (SIGTERM): while draining, new executes shed with a
        # retryable error and wait_drained() watches this in-flight count.
        self._draining = False
        self._inflight = 0
        # Repeat-offender accounting: CONSECUTIVE runner-killing limit
        # violations per lane (a clean request on the lane resets it). At
        # the breaker threshold the lane trips open for one cooldown — the
        # native failure count can't get there on its own because every
        # post-violation refill spawn succeeds and resets it.
        self._violation_strikes: dict[int, int] = {}
        # Fleet-wide persistent XLA compile cache: the hot set seeded into
        # every sandbox's cache dir at spawn and harvested back at
        # turnover/teardown, so the fleet compiles each kernel once
        # (services/compile_cache.py; the kill switch makes this a no-op
        # store that seeds and harvests nothing).
        self.compile_cache = compile_cache or CompileCacheStore.from_config(
            self.config
        )
        self._prewarm_started = False
        # Batched multi-chip execution lanes: eligible small jobs from one
        # tenant coalesce in a bounded window (services/batcher.py) and run
        # as ONE fused dispatch on a single multi-chip sandbox instead of N
        # serial round-trips. The kill switch (APP_BATCHING_ENABLED=0)
        # leaves this None and every request takes the exact serial path.
        self.batcher: Batcher | None = None
        if self.config.batching_enabled:
            self.batcher = Batcher(
                window_s=self.config.batch_window_ms / 1000.0,
                max_jobs=self.config.batch_max_jobs,
                dispatch=self._dispatch_batch,
            )
        # Demand-adaptive warm-pool autoscaling (services/autoscaler.py):
        # per-lane targets driven by arrival rate, queue depth, and the
        # scheduler's queue-wait/spawn EWMAs replace the static
        # executor_pod_queue_target_length constant as _lane_target's
        # input. The kill switch (APP_POOL_AUTOSCALE_ENABLED=0) makes
        # target() return the static constant — pre-autoscale behavior
        # byte-for-byte. Policy lives in the autoscaler; this class feeds
        # it snapshots and actuates (fill_pool up, the idle reaper down).
        self.autoscaler = PoolAutoscaler(
            self.config,
            clock=self.scheduler.now,
            metrics=self.metrics,
            tracer=self.tracer,
        )
        # Control-plane-wide taint for backends whose sandboxes SHARE one
        # cache dir (compile_cache_dir_scope == "shared": the local
        # backend's default mode). There, per-sandbox taint can't vouch
        # for the dir — any tenant run on ANY sandbox writes the same
        # path every other sandbox's harvest manifest lists — so the
        # first tenant execute ends harvesting for this control plane's
        # lifetime (the dir persists; the backend starts it empty, see
        # LocalSandboxBackend._fresh_cache_epoch). Pre-warm runs before
        # tenant load, so the store still fills in the trusted-only epoch.
        self._shared_cache_tainted = False
        # Per-chip lease fencing (services/leases.py): every spawn mints a
        # monotonic generation token per lease scope (the physical chip-set
        # — backend lease_scope, or the lane); a wedged verdict revokes the
        # lease (on_host_wedged → fence_host), so a stale claim can never
        # re-wedge a successor's chips. Fenced scopes re-admit only after
        # the configured clean-probe streak.
        self.leases = LeaseRegistry(
            readmit_streak=self.config.device_probe_readmit_streak,
            clock=self.scheduler.now,
            store=self.state_store,
        )
        # Actuation budget: fence timestamps per lane — at most
        # device_fence_max_per_window actuations per window, so a probe
        # false-positive storm cannot mass-dispose a serving lane.
        self._fence_times: dict[int, deque[float]] = {}
        # Performance anomaly plane (services/perf_observer.py): streaming
        # latency baselines per (lane, phase) and per tenant, EWMA-banded
        # drift verdicts, per-request device-memory accounting, and
        # auto-triggered profiling. The kill switch constructs a disabled
        # observer — no recording, no device-memory wire field, no
        # auto-profiles, no perf metric families: today's behavior
        # byte-for-byte.
        self.perf = perf or PerfObserver(
            self.config,
            metrics=self.metrics,
            tracer=self.tracer,
            clock=self.scheduler.now,
        )
        # Telemetry-plane attachments (set by the application context): the
        # device-health probe daemon and the OTLP exporter, surfaced through
        # GET /statusz. Optional — the executor runs fine without either.
        self.device_health = None
        self.otlp_exporter = None
        # Deterministic result memoization (services/result_memo.py): a
        # declared-pure run that completed limit-clean is recorded keyed on
        # everything that could change its output, and a later identical
        # request serves from the record at admission — no scheduler ticket,
        # no sandbox round-trip, no chip-second billed. The index rides the
        # state store above (coherent across replicas); the kill switch
        # constructs a disabled store and every path is pre-memo
        # byte-for-byte.
        self.result_memo = ResultMemoStore.from_config(
            self.config, self.state_store, self.storage, metrics=self.metrics
        )
        # Session durability plane (services/session_store.py): idle
        # sessions checkpoint (interpreter state + workspace manifest) into
        # this store, dispose their sandbox, and release the chip through
        # _session_held — the autoscaler sees reclaimed supply — then
        # restore lazily on their next turn, session_seq continuous. The
        # same path migrates live sessions off fenced hosts. The index
        # rides the state store (a session hibernated behind replica A
        # restores behind replica B); the kill switch constructs a
        # disabled store and every session path is pre-durability
        # byte-for-byte (pin-forever semantics).
        self.session_store = SessionStore.from_config(
            self.config, self.state_store, self.storage, metrics=self.metrics
        )
        # Satellite observability: cumulative parked-idle chip-seconds the
        # sweeper has accounted (the reclaimed-supply justification metric,
        # also a statusz field).
        self._idle_chip_seconds = 0.0
        # The executor-binary component of every memo key, computed once: a
        # binary upgrade changes the key and old records miss.
        self._memo_binary_key = (
            binary_key_of(
                str(getattr(self.backend, "binary", "") or "")
                or self.config.executor_binary,
                self.config.executor_image,
            )
            if self.result_memo.enabled
            else ""
        )
        # One persistent client for all sandbox HTTP: connection pooling
        # keeps per-request TCP setup off the Execute path.
        self._client: httpx.AsyncClient | None = None
        # Keep-alive reuse proof for the pooled client: ids of network
        # streams already seen on a response — a repeat id is a dispatch
        # that skipped TCP (+TLS) setup entirely.
        self._seen_streams: set[int] = set()
        self.metrics.bind_pool(self._pools)
        self.metrics.bind_sessions(self._sessions)
        self.metrics.bind_breakers(self.breakers)
        self.metrics.bind_scheduler(self.scheduler)
        self.metrics.bind_compile_cache(self.compile_cache)
        self.metrics.bind_autoscale(self)
        self.metrics.bind_quotas(self.quotas)
        self.metrics.bind_perf(self.perf)
        self.metrics.bind_result_memo(self.result_memo)

    async def _count_stream_reuse(self, response) -> None:
        """Response event hook on the shared client: count dispatches that
        rode an already-established keep-alive connection. httpcore exposes
        the underlying socket as the identity-stable `network_stream`
        extension — a repeat id is a request that paid zero TCP setup.
        Mock/fault transports lack the extension; the hook no-ops there."""
        stream = response.extensions.get("network_stream")
        if stream is None:
            return
        key = id(stream)
        if key in self._seen_streams:
            self.metrics.executor_connections_reused.inc()
        else:
            self._seen_streams.add(key)
            # Bound the id set: a long-lived control plane churns sockets
            # (pool expiry, sandbox turnover) and ids recycle with them.
            if len(self._seen_streams) > 4096:
                self._seen_streams.clear()
                self._seen_streams.add(key)

    def _http_client(self) -> httpx.AsyncClient:
        if self._client is None or self._client.is_closed:
            # A fault-injecting backend supplies a transport that drops a
            # seeded fraction of requests on the wire (chaos testing the
            # mid-execute connection-loss path); real backends supply none.
            transport_fn = getattr(self.backend, "http_transport", None)
            transport = transport_fn() if transport_fn is not None else None
            # Explicit keep-alive pooling, tuned for the fleet shape: each
            # sandbox host gets a persistent connection (the C++ server
            # runs an HTTP/1.1 keep-alive loop), and the expiry comfortably
            # outlives a pool-idle gap so sequential dispatches to one host
            # reuse one TCP connection instead of re-handshaking
            # (executor_connections_reused_total proves it).
            limits = httpx.Limits(
                max_connections=max(
                    64, 4 * self.config.executor_pod_queue_target_length
                ),
                max_keepalive_connections=64,
                keepalive_expiry=30.0,
            )
            self._client = httpx.AsyncClient(
                timeout=httpx.Timeout(30.0),
                transport=transport,
                limits=limits,
                event_hooks={"response": [self._count_stream_reuse]},
            )
        return self._client

    # ------------------------------------------------------------ degradation

    def degraded(self) -> bool:
        """Is the control plane in degraded mode? True while the DEFAULT
        lane's spawn breaker is hard-open (the lane an Execute without an
        explicit chip_count lands on — config.default_chip_count, not a
        literal lane 0): new work there fails fast, so health surfaces must
        advertise NOT_SERVING/503 and shed load until a half-open probe
        succeeds."""
        return self.breakers.is_open(self.config.default_chip_count)

    def degraded_retry_after(self) -> float:
        """Seconds a shedding response should tell clients to wait
        (Retry-After); 0 when serving normally."""
        return self.breakers.retry_after(self.config.default_chip_count)

    def lane_degraded(self, chip_count: int) -> bool:
        """Per-lane degradation, for gRPC health's per-service-name
        reporting (`lane-<n>`): a dead 4-chip nodepool must read
        NOT_SERVING on `lane-4` while CPU-lane traffic stays SERVING."""
        return self.breakers.is_open(chip_count)

    # ----------------------------------------------------------------- drain

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self) -> int:
        """Execute/execute_stream requests currently running end to end
        (admission through release hand-off)."""
        return self._inflight

    def begin_drain(self) -> None:
        """Stop admitting new executes (they shed with a retryable capacity
        error) while in-flight work runs to completion — the SIGTERM half of
        graceful shutdown; health surfaces flip alongside."""
        self._draining = True

    async def wait_drained(self, grace: float) -> bool:
        """Wait up to `grace` seconds for in-flight executes to finish.
        Returns True when the service drained fully (False = grace expired
        with work still running; close() will cut it off)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.0, grace)
        while self._inflight > 0 and loop.time() < deadline:
            await asyncio.sleep(0.05)
        return self._inflight == 0

    def _check_admission_open(self) -> None:
        if self._draining:
            raise SessionLimitError(
                "service is draining (shutting down); retry against "
                "another replica"
            )

    # ------------------------------------------------------------------ pool

    def _pool(self, chip_count: int) -> deque[Sandbox]:
        return self._pools.setdefault(chip_count, deque())

    # Sandbox device-health marks that disqualify a pooled host from
    # SERVING: wedged (device plane dead), draining (fenced, dispose in
    # flight), recovering (on a fenced scope, still earning its clean-probe
    # streak).
    _UNSERVABLE_HEALTH = frozenset({"wedged", "draining", "recovering"})

    def _pool_supply(self, chip_count: int) -> int:
        """Pooled sandboxes that can actually serve. Wedged hosts hold a
        deque slot until the fencing actuator drains them (or, with the
        actuation kill switch off, until an operator does); draining and
        recovering hosts are quarantined by design — none of them count as
        supply, or a lane of zombies would read "full" and never refill."""
        pool = self._pools.get(chip_count)
        if not pool:
            return 0
        return sum(
            1
            for sandbox in pool
            if sandbox.meta.get("device_health") not in self._UNSERVABLE_HEALTH
        )

    def _pool_standby(self, chip_count: int) -> int:
        """Pooled RECOVERING hosts: supply-in-transit, like an in-flight
        spawn — they hold their physical chips and will serve once the
        clean-probe streak re-admits them, so refills must count them
        (spawning replacements for hosts that are about to re-admit would
        stampede the backend and, on a constrained lane, deadlock on the
        chips the recovering host still owns). Wedged/draining hosts are
        NOT standby: their chips are being reclaimed, and the refill that
        replaces them is exactly the point."""
        pool = self._pools.get(chip_count)
        if not pool:
            return 0
        return sum(
            1
            for sandbox in pool
            if sandbox.meta.get("device_health") == "recovering"
        )

    def _known_lanes(self) -> set[int]:
        """Every lane with any pool presence (pooled, in-use, spawning,
        session-parked) or autoscaler state — ONE membership rule shared
        by the sweep, the /healthz supply rows, and the autoscale gauges,
        so a lane can never be managed but invisible (or vice versa)."""
        return (
            set(self._pools)
            | set(self._in_use)
            | set(self._spawning)
            | set(self._session_held)
            | set(self.autoscaler.lanes())
        )

    def _lane_snapshot(self, chip_count: int, *, queued: int | None = None) -> LaneSnapshot:
        """The autoscaler's per-lane demand/supply instant."""
        return LaneSnapshot(
            queued=self.scheduler.queued(chip_count) if queued is None else queued,
            # Demand counts only sandboxes an ACTIVE request holds;
            # mid-release holds are supply-in-transit, not load.
            in_use=max(
                0,
                self._in_use.get(chip_count, 0)
                - self._releasing.get(chip_count, 0),
            ),
            pooled=self._pool_supply(chip_count),
            spawning=self._spawning.get(chip_count, 0),
            recovering=self._pool_standby(chip_count),
            draining=self._draining_count(chip_count),
            queue_wait_ewma=self.scheduler.queue_wait_ewma(chip_count),
            spawn_ewma=self.scheduler.spawn_ewma(chip_count),
            # Explicit hibernated-wake supply signal (session durability
            # plane): parked sessions whose wake would land on this lane.
            # Cached inside the session store; {} when durability is off.
            hibernated=self.session_store.hibernated_by_lane().get(
                chip_count, 0
            ),
        )

    def _draining_count(self, chip_count: int) -> int:
        """LIVE fenced hosts of the lane still being disposed (pooled or
        not): the /healthz + snapshot observability of an in-flight
        drain-and-replace."""
        return sum(
            1
            for lane, sandbox in self._live_sandboxes.values()
            if lane == chip_count and sandbox.meta.get("lease_fenced")
        )

    def _lane_capacity(self, chip_count: int) -> int | None:
        capacity_fn = getattr(self.backend, "pool_capacity", None)
        capacity = capacity_fn(chip_count) if capacity_fn is not None else None
        if (
            capacity is not None
            and self._store_shared
            # Backends whose capacity names REPLICA-LOCAL hardware (each
            # replica brought its own node pool) opt out: peers' holds
            # don't contend for these chips.
            and getattr(self.backend, "capacity_shared_across_replicas", True)
        ):
            # N replicas share one physical substrate (the k8s cluster's
            # chips, or one machine's TPU): subtract what PEERS currently
            # hold so their spawn-vs-wait decisions cooperate. The
            # cooperation is BOUNDED-STALENESS (gauges publish at the
            # spawn claim, reads cache 0.25s), not an atomic reservation:
            # two replicas racing the last slot inside one freshness
            # window both spawn, and the overshoot degrades to what the
            # physical backend arbitrates anyway — a queued/failed spawn
            # — never to corruption. Stale gauges (dead replica) age out
            # on the heartbeat TTL so a crashed peer's holds stop gating
            # the survivors.
            capacity = max(0, capacity - self._peer_busy(chip_count))
        return capacity

    # ------------------------------------------------- cross-replica state

    def _publish_occupancy(self, lane: int) -> None:
        """Publish this replica's physical holds on the lane (in-use +
        session-held + in-flight spawns) into the shared store — the other
        half of `_lane_capacity`'s peer subtraction. No-op in
        single-replica mode."""
        if not self._store_shared:
            return
        busy = (
            self._in_use.get(lane, 0)
            + self._session_held.get(lane, 0)
            + self._spawning.get(lane, 0)
        )
        try:
            self.state_store.put(
                "occupancy",
                f"{lane}/{self.replica_id}",
                {"busy": busy, "ts": time.time()},
            )
        except Exception:  # noqa: BLE001 — a gauge write must not fail serving
            logger.warning("occupancy publish failed", exc_info=True)

    def _peer_busy(self, lane: int) -> int:
        """Sum of PEER replicas' fresh occupancy gauges for the lane.
        The store scan is bounded by a short freshness window (the
        breaker's _remote_cache discipline): _lane_capacity sits on the
        hot acquire path, and occupancy staleness of a quarter second is
        already inside the sweep-kick staleness bound."""
        now = time.time()
        expires, cached = self._peer_busy_cache.get(lane, (0.0, 0))
        if now < expires:
            return cached
        ttl = max(1.0, self.config.replica_heartbeat_ttl)
        total = 0
        try:
            rows = self.state_store.items("occupancy")
        except Exception:  # noqa: BLE001 — degraded store reads as empty
            # Cache the failure verdict too: a degraded store must not be
            # re-scanned (up to the sqlite busy timeout, on the event
            # loop) by every capacity check.
            self._peer_busy_cache[lane] = (now + 0.25, 0)
            return 0
        for key, record in rows.items():
            row_lane, _, rid = key.partition("/")
            if row_lane != str(lane) or rid == self.replica_id:
                continue
            if not isinstance(record, dict):
                continue
            ts = record.get("ts")
            busy = record.get("busy")
            if (
                isinstance(ts, (int, float))
                and now - ts <= ttl
                and isinstance(busy, (int, float))
            ):
                total += max(0, int(busy))
        self._peer_busy_cache[lane] = (now + 0.25, total)
        return total

    def _notify_lane(self, chip_count: int) -> None:
        """Capacity turnover on the lane: the scheduler wakes the next
        waiter in fair order (an explicit grant, not a broadcast)."""
        self._publish_occupancy(chip_count)
        self.scheduler.kick(chip_count)

    def _notify_all_lanes(self) -> None:
        """Wake waiters on EVERY lane: freed capacity on a constrained
        backend is shared across lanes (see _session_held_constrained), so a
        session closing in lane 0 can unblock a lane-4 waiter."""
        self.scheduler.kick_all()

    def _session_held_constrained(self) -> int:
        """Session-parked sandboxes summed over ALL capacity-constrained
        lanes. Constrained lanes are treated as one shared physical
        substrate — the same model behind _evict_idle_other_lanes: on the
        local backend every warm-JAX sandbox holds the same exclusive TPU
        regardless of lane, so a session parked in lane 0 must gate lane 4's
        spawns too (per-lane counting would wedge those spawns behind libtpu
        for the session's whole lifetime). On backends whose lanes are truly
        separate pools this over-counts — a spawn then waits for a session
        to close when it needn't — which errs on the safe side."""
        capacity_fn = getattr(self.backend, "pool_capacity", None)
        if capacity_fn is None:
            return 0
        return sum(
            held
            for lane, held in self._session_held.items()
            if held and capacity_fn(lane) is not None
        )

    def _lane_target(self, chip_count: int, *, extra_free: int = 0) -> int:
        """Warm-pool target for a lane, capped by the backend's physical
        capacity: a warm TPU sandbox owns its chips for its whole pool
        residency, so an uncapped target (the reference's flat 5,
        config.py:77) would demand N× the chips of one request — wedging
        spawns behind libtpu's exclusive access locally, or pods Pending on
        Kubernetes. CPU lanes report no cap and keep the configured target.

        `extra_free` lets a closing session's turnover treat its own slot as
        available for the recycle decision while `_session_held` still counts
        it (the slot is only truly free once the sandbox is pooled/disposed).

        The uncapped input is the autoscaler's dynamic per-lane target
        (demand model: arrival rate, queue depth, queue-wait/spawn EWMAs);
        with the kill switch off it IS the static constant, so this method
        behaves exactly as before autoscaling existed."""
        target = self.autoscaler.target(chip_count)
        capacity = self._lane_capacity(chip_count)
        if capacity is not None:
            # Session-held sandboxes occupy physical slots for their whole
            # session lifetime — the pool must not demand the chips back.
            capacity = max(
                0, capacity - self._session_held_constrained() + extra_free
            )
            target = min(target, capacity)
        return target

    async def fill_pool(self, chip_count: int = 0) -> None:
        """Top the lane up to the target length, tracking in-flight spawns.

        In-use sandboxes count toward the target when reuse is on: they
        return to the pool at release (generation turnover), so spawning a
        replacement would overshoot — and on a capacity-constrained backend,
        deadlock against the in-flight request for the physical TPU slot."""
        if self._closed:
            return
        if self.breakers.is_open(chip_count):
            # Refill spawns against an open breaker would only feed its
            # failure count; the half-open probe (first real request after
            # cooldown) is what re-tests the backend.
            logger.debug(
                "pool refill skipped: lane-%d breaker open", chip_count
            )
            return
        pool = self._pool(chip_count)
        target = self._lane_target(chip_count)
        in_use = (
            self._in_use.get(chip_count, 0)
            if self.config.executor_reuse_sandboxes
            else 0
        )
        spawning = self._spawning.get(chip_count, 0)
        # Supply counts only servable pooled hosts (wedged/draining zombies
        # must be refilled past — their disposal is the fencing actuator's
        # job), plus recovering standby (due to re-admit; spawning past
        # them would overshoot and fight them for chips).
        missing = (
            target
            - self._pool_supply(chip_count)
            - self._pool_standby(chip_count)
            - spawning
            - in_use
        )
        if missing <= 0:
            return
        # Cap CONCURRENT refill spawns per lane: a large target jump
        # (exactly what autoscaling makes possible) must ramp in bounded
        # waves, not stampede the k8s API / libtpu attach path with every
        # missing sandbox at once. The tail of a capped fill re-arms below
        # once this wave lands.
        burst = self.config.pool_spawn_burst
        if burst > 0:
            missing = min(missing, max(0, burst - spawning))
            if missing <= 0:
                return
        self._spawning[chip_count] = self._spawning.get(chip_count, 0) + missing
        self._publish_occupancy(chip_count)
        succeeded = 0

        async def spawn_one() -> None:
            nonlocal succeeded
            try:
                # traced_seed=False: a refill task inherits whatever trace
                # context was current when fill_pool_soon fired, and a seed
                # span finishing after that request's trace is read would
                # make its span set nondeterministic. (Retry EVENTS still
                # attach while the requester's acquisition span is open —
                # exactly when they're relevant — and are silently dropped
                # once it has exported, the long-standing event semantics.)
                sandbox = await self._spawn_with_retry(
                    chip_count, traced_seed=False
                )
                if self._closed:
                    await self._dispose(sandbox)
                else:
                    sandbox.meta["pooled_at"] = self.scheduler.now()
                    pool.append(sandbox)
                    succeeded += 1
            except SandboxSpawnError:
                # degraded pool: log and continue (parity: reference logs and
                # keeps going, kubernetes_code_executor.py:184-194)
                logger.exception("pool prefill spawn failed (lane=%d)", chip_count)
            except CircuitOpenError as e:
                # The breaker opened while this refill was in flight (e.g.
                # a sibling spawn crossed the threshold): stop quietly — the
                # lane refills on the first request after a successful probe.
                logger.warning("pool prefill stopped (lane=%d): %s", chip_count, e)
            except StateStoreDegradedError as e:
                # Lease mints fail closed while the shared store is down:
                # background refills stop quietly (the lane refills on the
                # first acquire after the store heals) instead of escaping
                # the gather.
                logger.warning(
                    "pool prefill paused (lane=%d): %s", chip_count, e
                )
            finally:
                self._spawning[chip_count] -= 1
                self._notify_lane(chip_count)

        await asyncio.gather(*(spawn_one() for _ in range(missing)))
        if (
            burst > 0
            and succeeded > 0
            and not self._closed
            and self._pool_supply(chip_count)
            + self._pool_standby(chip_count)
            + self._spawning.get(chip_count, 0)
            + (
                self._in_use.get(chip_count, 0)
                if self.config.executor_reuse_sandboxes
                else 0
            )
            < self._lane_target(chip_count)
        ):
            # Burst-capped ramp: this wave landed and the lane is still
            # short — continue toward the target. Only re-arm on at least
            # one success, so a persistently failing backend degrades to
            # the pre-existing "log and refill on next acquire" behavior
            # instead of a hot retry loop.
            self.fill_pool_soon(chip_count)

    def fill_pool_soon(self, chip_count: int = 0) -> None:
        if self._closed:
            return
        task = asyncio.create_task(self.fill_pool(chip_count))
        self._fill_tasks.add(task)
        task.add_done_callback(self._fill_tasks.discard)

    async def _spawn_with_retry(
        self, chip_count: int, *, traced_seed: bool = True
    ) -> Sandbox:
        """Spawn with the retry engine + circuit breaker: bounded, jittered
        retries on SandboxSpawnError; every attempt first consults the
        lane's breaker, so a breaker opened mid-ladder (by this spawn's own
        failures or a sibling's) aborts the remaining attempts immediately
        with a retryable CircuitOpenError instead of hammering a backend
        that is down. `traced_seed` is True only for spawns AWAITED on a
        request path, where the compile-cache seed span deterministically
        finishes inside the request's trace."""
        breaker = self.breakers.lane(chip_count)

        async def attempt() -> Sandbox:
            breaker.check(chip_count)
            # Evict on EVERY attempt, not once before the retry loop: a
            # cross-lane refill that was mid-flight during the first eviction
            # can park an idle slot-holding sandbox right after it, and only
            # a fresh eviction at the next attempt can free that slot again.
            await self._evict_idle_other_lanes(chip_count)
            start = time.perf_counter()
            try:
                sandbox = await self.backend.spawn(chip_count)
            except SandboxSpawnError as e:
                # Backends with watch-path breaker integration mark errors
                # they already counted (kubernetes records one strike per
                # failed host watch) — counting the surfaced aggregate again
                # would open the lane faster than the configured threshold.
                if not getattr(e, "breaker_recorded", False):
                    breaker.record_failure()
                raise
            breaker.record_success()
            elapsed = time.perf_counter() - start
            self.metrics.spawn_seconds.observe(
                elapsed, chip_count=str(chip_count)
            )
            # Feed the scheduler's spawn-latency EWMA: one input to
            # deadline-aware admission when the warm pool is empty.
            self.scheduler.observe_spawn(chip_count, elapsed)
            # Per-chip lease FIRST: mint this sandbox's generation token
            # and push it to every host's executor before the sandbox
            # becomes visible anywhere — a stale-generation claim against
            # these chips must be distinguishable from the host's first
            # observable instant, not after a push races the first
            # dispatch. If the scope is recovering (the predecessor was
            # fenced), the replacement starts quarantined: probed, counted
            # as standby, handed nothing until the clean-probe streak
            # re-admits it.
            try:
                await self._attach_lease(sandbox, chip_count)
            except StateStoreDegradedError:
                # Mint failed closed (shared store down) AFTER the backend
                # spawn succeeded: the sandbox exists but can never be
                # granted — dispose it rather than leak a live host with
                # no lease, and surface the typed refusal (NOT a
                # SandboxSpawnError: retrying inside the same outage
                # window just burns spawns).
                await self._dispose(sandbox)
                raise
            # Register with the live-host inventory the probe daemon walks
            # (dropped again in _dispose).
            self._live_sandboxes[sandbox.id] = (chip_count, sandbox)
            # Seed the fleet's hot compile set into the fresh sandbox's
            # cache dir BEFORE it serves: the kernels someone already
            # compiled load from cache instead of recompiling. Best-effort
            # and cheap (O(hot set), conditional PUTs) — never fails a
            # spawn.
            await self._seed_compile_cache(sandbox, traced=traced_seed)
            return sandbox

        def on_retry(failures: int, error: BaseException, delay: float) -> None:
            self.metrics.retry_attempts.inc(operation="spawn")
            tracing.add_event(
                "retry",
                operation="spawn",
                attempt=failures,
                delay_s=round(delay, 3),
                error=str(error)[:200],
            )

        return await retry_async(
            attempt, self._spawn_retry_policy, on_retry=on_retry
        )

    async def _evict_idle_other_lanes(self, chip_count: int) -> None:
        """On a capacity-constrained backend, idle warm sandboxes pooled in
        OTHER lanes hold the physical TPU slots this lane's spawn needs —
        without eviction the spawn would block on the slot until timeout
        (starvation across lanes). Disposal is awaited so the slots are
        actually free before the spawn starts; the evicted lanes refill only
        when next requested."""
        capacity_fn = getattr(self.backend, "pool_capacity", None)
        if capacity_fn is None or capacity_fn(chip_count) is None:
            return
        evicted = [
            sandbox
            for lane, pool in self._pools.items()
            # Only lanes that actually hold constrained resources: draining
            # an unconstrained lane (e.g. CPU pods on kubernetes) would wipe
            # a warm pool without freeing anything.
            if lane != chip_count and capacity_fn(lane) is not None
            for sandbox in _drain(pool)
        ]
        if evicted:
            logger.info(
                "evicting %d idle sandbox(es) from other lanes to free TPU "
                "slots for lane %d",
                len(evicted),
                chip_count,
            )
            await asyncio.gather(*(self._dispose(s) for s in evicted))

    # ------------------------------------------------- lease fencing & wedge
    # recovery: the actuation half of the device-health story. The probe
    # daemon detects (PR 8); these methods act — lease revocation, lane
    # drain, dispose-and-replace, and the recovering-scope quarantine.

    def _lease_scope(self, chip_count: int, sandbox: Sandbox | None = None) -> str:
        """The lease scope a lane's sandboxes attach on: the backend's own
        hardware naming when it has one (`lease_scope(chip_count)`), else
        the chip-count lane — which on the local backend IS the chip-set
        (every warm sandbox holds the same physical TPU). Scopes name
        hardware, not sandboxes: that is what lets "the replacement on the
        same chips must re-earn trust" be expressed at all.

        Backends that can name PER-HOST hardware (kubernetes: the node/
        slice a pod landed on) take the sandbox too — fencing then
        quarantines exactly the wedged node's chips instead of the whole
        chip-count lane (the PR 13 carried follow-up). Callers without a
        sandbox in hand (the lane-level recovering gate) get the lane
        default, which such backends treat as the coarse parent scope."""
        scope_fn = getattr(self.backend, "lease_scope", None)
        if scope_fn is not None:
            try:
                scope = scope_fn(chip_count, sandbox=sandbox)
            except TypeError:
                # Older single-arg backends (and wrappers) keep working.
                scope = scope_fn(chip_count)
            if isinstance(scope, str) and scope:
                return scope
        return f"lane-{chip_count}"

    async def _attach_lease(self, sandbox: Sandbox, chip_count: int) -> None:
        """Mint the sandbox's generation token and record it on every host
        executor (POST /lease). Best-effort on the wire: an old binary
        (404) or a transient failure leaves the host without executor-side
        enforcement — the control-plane revocation check still fences it —
        and never fails a spawn."""
        scope = self._lease_scope(chip_count, sandbox)
        lease = self.leases.mint(scope, sandbox.id)
        sandbox.meta["lease"] = lease
        if self.leases.recovering(scope):
            sandbox.meta["device_health"] = "recovering"
        if self._store_shared:
            # Fleet host registry: which replica owns which host, on what
            # scope/generation — the shared-store view a peer (or an
            # operator reading any replica's /statusz) can join against.
            try:
                self.state_store.put(
                    "hosts",
                    sandbox.id,
                    {
                        "replica": self.replica_id,
                        "lane": chip_count,
                        "scope": scope,
                        "generation": lease.generation,
                        "ts": time.time(),
                    },
                )
            except Exception:  # noqa: BLE001
                logger.warning("host registry publish failed", exc_info=True)
        if not self.config.device_fence_enabled:
            return
        # Backends whose sandboxes are not real HTTP hosts (the in-memory
        # test fake) opt out of the wire push: minting stays (the
        # control-plane revocation check needs no wire), and skipping the
        # doomed POSTs keeps the seeded chaos suites' interleaving
        # deterministic — real-socket connect failures would re-deal which
        # request consumes which fault draw between runs.
        if getattr(self.backend, "supports_lease_push", True) is False:
            return
        client = self._http_client()

        async def push(url: str) -> None:
            try:
                await client.post(
                    f"{url}/lease",
                    json={"token": lease.wire_token},
                    timeout=5.0,
                )
            except httpx.HTTPError:
                logger.debug(
                    "lease push to %s failed (control-plane fencing still "
                    "covers it)",
                    url,
                )

        await asyncio.gather(*(push(url) for url in sandbox.host_urls))

    def _check_lease(self, sandbox: Sandbox) -> None:
        """Refuse to dispatch against a revoked lease: the fence landed
        while this request held (or was about to use) the sandbox. A clean
        refusal BEFORE the wire hop — the fenced host's device plane never
        sees the claim, the stateless retry ladder replays on a fresh
        sandbox, and a session gets the standard typed close."""
        lease = sandbox.meta.get("lease")
        if isinstance(lease, Lease) and self.leases.stale(lease):
            # Locally revoked (this replica fenced it), or at-or-below the
            # scope's shared fence floor (a PEER replica fenced the
            # hardware) — either way the claim must never reach the chips.
            raise StaleLeaseError(
                f"sandbox {sandbox.id} lease {lease.wire_token} was fenced "
                f"({lease.revoke_reason or 'fenced'}); the request must "
                "move to a healthy host",
                scope=lease.scope,
            )

    def _wire_headers(self, sandbox: Sandbox) -> dict | None:
        """Headers for a sandbox execute hop: trace propagation plus the
        sandbox's lease token — the executor rejects a token older than
        the one it holds with the typed 409 before taking any lock."""
        headers = self._trace_headers() or {}
        lease = sandbox.meta.get("lease")
        if isinstance(lease, Lease):
            headers["x-lease-token"] = lease.wire_token
        return headers or None

    @staticmethod
    def _raise_if_stale_lease(resp, sandbox: Sandbox) -> None:
        """Map the executor's typed ``409 stale_lease`` refusal to
        StaleLeaseError (409 also means other things on other routes —
        only the typed body counts)."""
        if resp.status_code != 409:
            return
        try:
            body = resp.json()
        except ValueError:
            return
        if isinstance(body, dict) and body.get("error") == "stale_lease":
            raise StaleLeaseError(
                f"sandbox {sandbox.id} rejected a stale lease claim "
                f"(held {body.get('held')!r}, offered {body.get('offered')!r})"
            )

    def _fence_budget_ok(self, lane: int) -> bool:
        """The actuation budget: admit this fence only if the lane has
        fenced fewer than the cap inside the sliding window. The cap is
        what keeps a probe false-positive storm from mass-disposing a
        serving lane — past it, verdicts defer (and re-assert each probe
        cycle) until the window slides."""
        cap = self.config.device_fence_max_per_window
        if cap <= 0:
            return True
        window = max(1.0, self.config.device_fence_window_seconds)
        now = self.scheduler.now()
        times = self._fence_times.setdefault(lane, deque())
        while times and times[0] <= now - window:
            times.popleft()
        if len(times) >= cap:
            return False
        times.append(now)
        return True

    def on_host_wedged(self, sandbox_id: str, *, reason: str = "wedged") -> None:
        """The probe daemon's actuation hook: schedule fence-and-replace
        for a wedged host, off the probe cycle (disposal can block on a
        wedged process's kill). Idempotent per sandbox — the probe
        re-asserts every cycle and this dedupes on the fence mark."""
        if not self.config.device_fence_enabled or self._closed:
            return
        entry = self._live_sandboxes.get(sandbox_id)
        if entry is None or entry[1].meta.get("lease_fenced"):
            return
        task = asyncio.get_running_loop().create_task(
            self._off_request_path(self.fence_host(sandbox_id, reason=reason))
        )
        self._dispose_tasks.add(task)
        task.add_done_callback(self._dispose_tasks.discard)

    async def fence_host(self, sandbox_id: str, *, reason: str = "wedged") -> str:
        """Fence one wedged host and replace it: revoke its lease (stale
        claims die typed), drain it from the lane (pool slot freed, parked
        sessions closed so their clients reconnect to healthy hosts,
        in-flight requests keep the existing fault/serial-fallback
        semantics when the dispose cuts them off), dispose it through the
        standard path, and refill the lane. Returns the outcome (also the
        device_fence_total label): fenced / already_fenced / gone /
        breaker_open / budget_exhausted / disabled."""
        if not self.config.device_fence_enabled:
            return "disabled"
        entry = self._live_sandboxes.get(sandbox_id)
        if entry is None:
            return "gone"
        lane, sandbox = entry
        if sandbox.meta.get("lease_fenced"):
            return "already_fenced"
        if self.breakers.is_open(lane):
            # The lane cannot spawn replacements while its breaker is open:
            # disposing supply now would deepen the outage for zero gain.
            # The verdict stands and re-asserts after the cooldown.
            self.metrics.device_fences.inc(
                lane=str(lane), outcome="breaker_open"
            )
            return "breaker_open"
        if not self._fence_budget_ok(lane):
            self.metrics.device_fences.inc(
                lane=str(lane), outcome="budget_exhausted"
            )
            logger.warning(
                "wedge actuation deferred (lane=%d sandbox=%s): fence "
                "budget exhausted (%d per %.0fs) — probe storm suspected",
                lane,
                sandbox_id,
                self.config.device_fence_max_per_window,
                self.config.device_fence_window_seconds,
            )
            return "budget_exhausted"
        # Commit: mark first (the dedupe + the probe's DRAINING overlay +
        # the turnover guard all key off this), then revoke the lease so
        # every dispatch path refuses the host from this instant.
        sandbox.meta["lease_fenced"] = True
        sandbox.meta["device_health"] = "draining"
        lease = sandbox.meta.get("lease")
        if isinstance(lease, Lease):
            self.leases.fence(lease, reason=reason)
        # Drain: free the pool slot (queued work reroutes via the
        # scheduler's kicks once the replacement lands)...
        pool = self._pools.get(lane)
        if pool is not None:
            try:
                pool.remove(sandbox)
            except ValueError:
                pass
        # ...and get every session parked on this host OFF it NOW, not when
        # the client times out. With the durability plane live, each
        # session is MIGRATED: snapshot-then-restore-elsewhere — awaited
        # INLINE, before the dispose below kills the host — so its next
        # turn restores behind any replica with session_seq continuous and
        # zero client-visible state loss. A migration that cannot complete
        # (snapshot refused, lock held past the budget, durability off)
        # falls back to the pre-durability force-close: the session's next
        # request recreates against a healthy host (session_seq=1 reports
        # the state loss), instead of dispatching into the wedge and
        # hanging out its timeout. Snapshot traffic against the fenced
        # host is fine: the server-side lease token is still the one it
        # holds — only NEW claims at a successor die typed.
        for executor_id, session in list(self._sessions.items()):
            if session.sandbox is not sandbox or session.closed:
                continue
            migrated = False
            if self.session_store.enabled:
                try:
                    migrated = await self._migrate_session(
                        executor_id, session, reason
                    )
                except Exception:  # noqa: BLE001 — fall back to force-close
                    logger.warning(
                        "session %s migration off fenced host %s failed",
                        executor_id,
                        sandbox.id,
                        exc_info=True,
                    )
            if migrated:
                logger.warning(
                    "session %s migrated off fenced host %s (%s): state "
                    "checkpointed, restores on next turn",
                    executor_id,
                    sandbox.id,
                    reason,
                )
                continue
            if session.closed:
                continue
            logger.warning(
                "session %s force-closed: its host %s was fenced (%s)",
                executor_id,
                sandbox.id,
                reason,
            )
            self._end_session_soon(executor_id, session, recycle=False)
        self.metrics.device_fences.inc(lane=str(lane), outcome="fenced")
        self.tracer.record_span(
            "device_fence",
            trace_id=tracing.new_trace_id(),
            parent_id=None,
            start_unix=time.time(),
            duration_s=0.0,
            attributes={
                "lane": lane,
                "sandbox": sandbox.id,
                "reason": reason,
                "scope": lease.scope if isinstance(lease, Lease) else "",
                "generation": (
                    lease.generation if isinstance(lease, Lease) else 0
                ),
            },
            status="error",
        )
        logger.warning(
            "fenced wedged host (lane=%d sandbox=%s reason=%s): lease "
            "revoked, draining and replacing",
            lane,
            sandbox.id,
            reason,
        )
        # Dispose-and-replace: the standard dispose path (idempotent with
        # any in-flight release — backend.delete tolerates repeats), then
        # the standard refill machinery. An in-flight request on this host
        # loses its connection mid-op and surfaces through the existing
        # fault semantics; its own release finds the sandbox unservable
        # and no-ops.
        await self._dispose(sandbox)
        self._notify_lane(lane)
        self.fill_pool_soon(lane)
        return "fenced"

    async def _acquire(
        self,
        chip_count: int,
        *,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        jobs: int = 1,
    ) -> Sandbox:
        """Acquire a sandbox slot — `_acquire_slot` inside a trace span
        carrying the admission attributes; the scheduler's enqueue/grant/
        shed events and the breaker's rejections attach to this span.
        `jobs` > 1 is a batched dispatch's multi-job token: one queue
        position and one sandbox serving N coalesced requests."""
        with self.tracer.span(
            "scheduler.queue_wait",
            attributes={
                "lane": chip_count,
                "tenant": tenant or self.scheduler.default_tenant,
                "priority": priority or "interactive",
                "jobs": jobs,
            },
        ):
            return await self._acquire_slot(
                chip_count,
                tenant=tenant,
                priority=priority,
                deadline=deadline,
                jobs=jobs,
            )

    async def _acquire_slot(
        self,
        chip_count: int,
        *,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        jobs: int = 1,
    ) -> Sandbox:
        """Acquire a sandbox slot through the scheduler.

        A thin client of the scheduler's grant tokens: submit() runs
        admission control (per-tenant depth bound, deadline feasibility) and
        queues a ticket; each explicit grant wakes exactly one waiter — in
        weighted-fair, priority-aware order — which then runs the same
        pool-pop / spawn-vs-wait / breaker-fail-fast logic as before. The
        old 30s safety-net poll is gone: every turnover issues a grant, and
        a turnover landing mid-evaluation is remembered by the scheduler
        (pending kicks), so a wake-up cannot be lost."""
        pool = self._pool(chip_count)
        # Demand signal for the autoscaler BEFORE admission: the arriving
        # acquisition updates the lane's arrival-rate EWMA and applies any
        # scale-up immediately, so the refill this very request triggers
        # (fill_pool_soon below) already sees the raised target —
        # spawn-ahead for the rest of the burst behind it.
        self.autoscaler.observe_arrival(
            chip_count, self._lane_snapshot(chip_count), jobs=jobs
        )
        now = self.scheduler.now()
        # After this long without a sandbox, spawn regardless of what is
        # "due back" — a long-running in-flight execute must not block a
        # waiter on an unconstrained lane indefinitely.
        grace_deadline = now + 10.0
        # On a constrained lane no amount of waiting helps while active
        # sessions hold every slot — bound the wait and surface a
        # retryable error instead of an open-ended hang.
        acquire_deadline = (
            now + self.config.executor_acquire_timeout
            if self.config.executor_acquire_timeout > 0
            else None
        )
        # Admission control happens HERE, at arrival: depth-bound sheds and
        # infeasible deadlines raise retryable errors carrying a computed
        # Retry-After instead of burning the acquire budget first.
        ticket = self.scheduler.submit(
            chip_count,
            tenant=tenant,
            priority=priority,
            deadline=deadline,
            # Warm supply for the admission estimate: wedged pooled hosts
            # can't serve a granted pop usefully, so they don't count.
            pool_ready=self._pool_supply(chip_count),
            jobs=jobs,
            # Trusted (pre-warm) acquisitions queue like anyone but bill
            # nobody — internal warmup wait is not a tenant's queue wait.
            metered=not _trusted_source_var.get(),
        )
        sandbox: Sandbox | None = None
        try:
            while True:
                capacity = self._lane_capacity(chip_count)
                # Unconstrained lanes re-wake at the grace deadline even
                # without a grant: a spawn CREATES capacity rather than
                # consuming queued supply, so it needn't wait its fair turn.
                deadline_at = ticket.deadline_at if ticket is not None else None
                candidates = [
                    t for t in (acquire_deadline, deadline_at) if t is not None
                ]
                if capacity is None and now < grace_deadline:
                    candidates.append(grace_deadline)
                timeout_at = min(candidates) if candidates else None
                granted = await self.scheduler.wait_grant(
                    ticket, timeout_at=timeout_at
                )
                now = self.scheduler.now()
                spawning = self._spawning.get(chip_count, 0)
                in_use = self._in_use.get(chip_count, 0)
                session_held = self._session_held_constrained()
                if not granted and deadline_at is not None and now >= deadline_at:
                    # Admission let the request in on an estimate; reality
                    # disagreed. The declared start deadline has passed, so
                    # keeping the ticket queued can only waste the client's
                    # time — reject NOW with the same retryable signal as an
                    # arrival-time rejection.
                    raise DeadlineInfeasibleError(
                        f"deadline ({deadline:.1f}s) expired while queued "
                        f"for a lane-{chip_count} sandbox slot",
                        lane=chip_count,
                        tenant=ticket.tenant,
                        retry_after=self.scheduler.estimated_wait(
                            chip_count, pool_ready=len(pool)
                        ),
                    )
                if (
                    not granted
                    and acquire_deadline is not None
                    and now >= acquire_deadline
                ):
                    raise CapacityTimeoutError(
                        f"no lane-{chip_count} sandbox slot freed within "
                        f"{self.config.executor_acquire_timeout:.0f}s "
                        f"(in_use={in_use}, session_held={session_held}, "
                        f"capacity={capacity}); retry later"
                    )
                if granted and pool:
                    sandbox = self._pop_pool_sandbox(pool)
                    if sandbox is not None:
                        break
                    # Pool holds only recovering/draining quarantined hosts:
                    # nothing servable to pop — fall through to the
                    # spawn-vs-wait logic (which counts those hosts as
                    # standby on constrained lanes, so the waiter parks
                    # until re-admission kicks it rather than fighting the
                    # quarantined host for its chips).
                if (
                    self.breakers.is_open(chip_count)
                    and spawning == 0
                    and in_use == 0
                ):
                    # Pool empty, nothing in flight or due back, and the
                    # lane's backend is known-down: waiting out the acquire
                    # budget (up to 300s) cannot help — fail fast with the
                    # retryable circuit error instead.
                    self.breakers.lane(chip_count).check(chip_count)
                if capacity is not None:
                    # Constrained lane: a competing spawn would lose the
                    # physical-slot race to an in-flight refill or an
                    # about-to-recycle request — spawn only under capacity.
                    # Session-held sandboxes count ACROSS constrained lanes
                    # (shared physical substrate, as in the eviction logic):
                    # they own their chips until the session closes (the
                    # idle sweep bounds this). Recovering standby hosts
                    # count too: they hold their chips through the
                    # quarantine, and the re-admission settle kicks every
                    # lane the moment they can serve.
                    can_spawn = (
                        spawning
                        + in_use
                        + session_held
                        + self._pool_standby(chip_count)
                        < capacity
                    )
                else:
                    # Unconstrained lane: sandboxes "due back" are in-flight
                    # refills plus (with reuse on) in-use sandboxes that will
                    # recycle into the pool at release. Wait when supply
                    # covers the queue — a recycle lands in milliseconds, a
                    # fresh spawn takes seconds — but spawn when demand
                    # exceeds it (burst) or the grace deadline passes.
                    due_back = spawning + (
                        in_use if self.config.executor_reuse_sandboxes else 0
                    )
                    can_spawn = (
                        due_back == 0
                        or self.scheduler.queued(chip_count) > due_back
                        # >= to match wait_grant's timeout comparison: a
                        # waiter woken exactly at the grace boundary must
                        # spawn, not fall through to the acquire deadline.
                        or now >= grace_deadline
                    )
                if (
                    can_spawn
                    and self.leases.recovering(self._lease_scope(chip_count))
                    and (self._pool_standby(chip_count) > 0 or spawning > 0)
                ):
                    # The lane's lease scope is mid-quarantine (a fence's
                    # replacement is earning its clean-probe streak) and a
                    # standby replacement already exists or is on its way:
                    # a direct spawn would land on the SAME recovering
                    # hardware and hand it straight to this request —
                    # exactly the early-handout _pop_pool_sandbox refuses
                    # for pooled hosts. Constrained lanes were already
                    # covered by the standby capacity count; unconstrained
                    # lanes (where nothing counted standby) slipped
                    # through. Park in fair order instead — the
                    # re-admission settle kicks every lane the moment the
                    # standby can serve. (With NO standby anywhere, the
                    # spawn below still runs: its recovering-marked result
                    # is parked as the scope's probe target, never handed
                    # out — see the post-spawn check.)
                    can_spawn = False
                if can_spawn:
                    # Count the direct spawn in _spawning: a concurrent
                    # waiter evaluating the guards mid-spawn must see it, or
                    # two waiters would race past a capacity-1 check and the
                    # loser would starve on the backend's physical slot.
                    self._spawning[chip_count] = (
                        self._spawning.get(chip_count, 0) + 1
                    )
                    # Publish the claim BEFORE the spawn starts (peers'
                    # capacity subtraction sees it at the earliest
                    # possible instant, not after the grant settles).
                    self._publish_occupancy(chip_count)
                    # Leave the queue BEFORE spawning: this waiter now owns
                    # its own supply, so the grant passes to the next waiter,
                    # which re-evaluates against the bumped spawn count.
                    self.scheduler.complete(ticket)
                    ticket = None
                    try:
                        sandbox = await self._spawn_with_retry(chip_count)
                    finally:
                        self._spawning[chip_count] -= 1
                        self._notify_lane(chip_count)
                    if sandbox.meta.get("device_health") in (
                        "recovering",
                        "draining",
                    ):
                        # The spawn landed on a quarantined lease scope
                        # (the fence raced this spawn, or this spawn IS
                        # the fenced scope's first replacement): the
                        # sandbox must serve NOTHING until the clean-probe
                        # streak re-admits it. Park it as the scope's
                        # standby/probe target and rejoin the queue — the
                        # standby gate above stops the next loop from
                        # spawning again behind it.
                        sandbox.meta["pooled_at"] = self.scheduler.now()
                        pool.append(sandbox)
                        sandbox = None
                        self._notify_lane(chip_count)
                        ticket = self.scheduler.submit(
                            chip_count,
                            tenant=tenant,
                            priority=priority,
                            deadline=deadline,
                            pool_ready=self._pool_supply(chip_count),
                            jobs=jobs,
                            metered=not _trusted_source_var.get(),
                        )
                        continue
                    break
                if granted:
                    # Nothing to pop and must not spawn: back to sleep in
                    # fair position (or straight back to evaluation, if a
                    # turnover landed while this holder was deciding).
                    self.scheduler.rearm(ticket)
        except BaseException:
            if ticket is not None:
                self.scheduler.abandon(ticket)
            raise
        if ticket is not None:
            self.scheduler.complete(ticket)
        self._in_use[chip_count] = self._in_use.get(chip_count, 0) + 1
        self._publish_occupancy(chip_count)
        self.fill_pool_soon(chip_count)
        return sandbox

    def _pop_pool_sandbox(self, pool: deque) -> Sandbox | None:
        """Pop the next pooled sandbox for the current request, skipping
        hosts the device-health probe marked WEDGED while anything
        healthier is available (handing a fresh request to a wedged device
        buys a full acquire-budget hang). RECOVERING/DRAINING hosts are
        never popped at all — a fenced scope's replacement must finish its
        clean-probe streak before it serves, and that gate is only real if
        no "last resort" hands it out early; when the pool holds nothing
        else the method returns None and the caller falls through to its
        spawn-vs-wait logic (bounded: the re-admission settle kicks every
        lane). Trusted (pre-warm) requests additionally prefer an
        UNTAINTED sandbox: a recycled sandbox that ever ran tenant code is
        harvest-ineligible for life — running the trusted kernels there
        compiles fine but admits nothing. Wedged-as-last-resort is kept
        for kill-switch parity (with actuation off, a lane whose only
        pooled hosts are wedged zombies must still hand something out
        rather than livelock a constrained lane, the PR 8 behavior)."""
        if self._store_shared:
            # Shared-fence gate: a pooled host whose lease sits at-or-below
            # its scope's published fence floor was fenced by a PEER
            # replica — it must never be granted here ("a host fenced by A
            # is never granted by B"). Drain it through the standard
            # dispose path (lease-fenced turnover) so the lane refills with
            # a fresh-generation host instead of carrying a zombie slot.
            for candidate in [
                c
                for c in pool
                if not c.meta.get("lease_fenced")
                and isinstance(c.meta.get("lease"), Lease)
                and self.leases.stale(c.meta["lease"])
            ]:
                try:
                    pool.remove(candidate)
                except ValueError:
                    continue
                candidate.meta["lease_fenced"] = True
                candidate.meta["device_health"] = "draining"
                logger.warning(
                    "pooled host %s drained: its lease scope was fenced by "
                    "a peer replica",
                    candidate.id,
                )
                task = asyncio.get_running_loop().create_task(
                    self._off_request_path(self._dispose(candidate))
                )
                self._dispose_tasks.add(task)
                task.add_done_callback(self._dispose_tasks.discard)
        prefer_untainted = self.compile_cache.enabled and _trusted_source_var.get()
        fallback: int | None = None
        wedged_fallback: int | None = None
        for i, candidate in enumerate(pool):
            health = candidate.meta.get("device_health")
            if health in ("recovering", "draining"):
                continue
            if health == "wedged":
                if wedged_fallback is None:
                    wedged_fallback = i
                continue
            if prefer_untainted and self._cache_sync(candidate).tainted:
                if fallback is None:
                    fallback = i
                continue
            del pool[i]
            return candidate
        for index in (fallback, wedged_fallback):
            if index is not None:
                candidate = pool[index]
                del pool[index]
                return candidate
        return None

    # --------------------------------------------------------------- execute

    async def execute(
        self,
        source_code: str | None = None,
        *,
        source_file: str | None = None,
        files: dict[str, str] | None = None,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        profile: bool = False,
        executor_id: str | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        limits: dict | None = None,
        pure: bool = False,
    ) -> Result:
        """Run user code in a sandbox; returns output + changed files.

        `pure=True` is the client's purity declaration — this run reads no
        network, no randomness, no wall clock: its output is a function of
        its inputs. Declared-pure runs ride the result memo
        (services/result_memo.py): an identical earlier run serves from its
        record at admission with zero sandbox HTTP and zero chip-seconds
        billed; a miss executes normally and records for the next caller.
        The declaration is a promise, not a sandbox restriction — a false
        one only risks the declarer's own (tenant-scoped) repeat results.

        Exactly one of `source_code` (inline) / `source_file` (an absolute
        workspace path that must appear in `files`) is required. With
        ``profile=True`` the sandbox captures a JAX profiler trace of the run
        and ships it back as ``/workspace/profile.zip``.

        `tenant` / `priority` / `deadline` are admission-control inputs for
        the fair-share scheduler: tenant defaults to the shared tenant,
        priority is `interactive` (default) or `batch`, and deadline is
        "this request must START within N seconds" — infeasible deadlines
        are rejected at arrival with a retryable error.

        `limits` is this request's resource-budget override (keys from
        services.limits.LIMIT_KEYS); it layers over the configured default
        and lane budgets and is min-clamped by the server caps — a request
        can only tighten its box. Breaches surface as LimitExceededError
        with the typed violation kind, never retried.

        Without `executor_id` each request gets a pristine sandbox. With it,
        requests sharing the id run in ONE live sandbox whose workspace (and
        warm process) persists across them — session affinity (the upstream
        bee-code-interpreter's persistent-executor semantics; the reference
        fork carried the field but its single-use pods ignored it). Session
        requests are never retried on infrastructure failure: a retry would
        land on a fresh sandbox and silently drop the session's state.
        """
        env, executor_id = self._normalize_request(env, profile, executor_id)
        usage_tenant = self._usage_tenant(tenant)
        self._check_admission_open()
        # Quota enforcement sits HERE — before the scheduler, the batcher,
        # or any session machinery sees the request. A denied (or
        # quarantined) request is never enqueued and consumes zero
        # sandboxes; the typed QuotaExceededError maps to HTTP 429 /
        # gRPC RESOURCE_EXHAUSTED with Retry-After + x-quota-* metadata.
        # The declared cost rides along for the predicted-overrun check.
        quota = self._quota_admit(
            usage_tenant, chip_count=chip_count, timeout=timeout
        )
        # Result-memo admission check: AFTER the quota gate (hits are still
        # request-rate-governed — free answers are not unmetered answers)
        # and BEFORE the auto-profile arm below (a served-from-record
        # request must not eat the lane's one profiling arm).
        memo_key, memo_state = self._memo_admission(
            pure,
            executor_id=executor_id,
            profile=profile,
            source_code=source_code,
            source_file=source_file,
            files=files,
            env=env,
            chip_count=chip_count,
            tenant=tenant,
            limits=limits,
        )
        if memo_state == "lookup":
            record = await self.result_memo.lookup(memo_key)
            if record is not None:
                try:
                    result = self._memo_hit_result(record)
                    self._apply_quota_phases(result, quota)
                    self._count_memo_hit(result, usage_tenant)
                    return result
                finally:
                    self.quotas.release(quota)
            memo_state = "miss"
        # Auto-triggered profiling: a pending arm on this request's lane
        # (set by the drift detector or a p99 outlier) is consumed here,
        # AFTER admission — a denied request must not eat the arm. The
        # profiler env rides this request, and the contextvar marks it so
        # the pipeline harvests (and zero-bills) the artifact.
        env, auto_profile = self._maybe_auto_profile(env, chip_count, tenant)
        profile_token = _auto_profile_var.set(auto_profile)
        # The purity declaration rides the request's task tree only while a
        # record could come of it (a miss): _run_on_sandbox forwards it to
        # the executor for the hashed echo.
        pure_token = _pure_run_var.set(memo_state == "miss")
        self._inflight += 1
        try:
            if executor_id is not None:
                result = await self._execute_in_session(
                    executor_id,
                    source_code,
                    source_file=source_file,
                    files=files,
                    timeout=timeout,
                    env=env,
                    chip_count=chip_count,
                    tenant=tenant,
                    priority=priority,
                    deadline=deadline,
                    limits=limits,
                )
            elif self._batch_eligible(source_code, files, env, deadline):
                result = await self._execute_batched(
                    source_code,
                    timeout=timeout,
                    env=env,
                    chip_count=chip_count,
                    tenant=tenant,
                    priority=priority,
                    limits=limits,
                )
            else:
                result = await self._execute_with_retry(
                    source_code,
                    source_file=source_file,
                    files=files,
                    timeout=timeout,
                    env=env,
                    chip_count=chip_count,
                    tenant=tenant,
                    priority=priority,
                    deadline=deadline,
                    limits=limits,
                )
        except CircuitOpenError as e:
            self.metrics.breaker_rejections.inc(chip_count=str(e.lane))
            self.metrics.executions.inc(outcome="rejected")
            self._usage_request(usage_tenant, "rejected")
            raise
        except LimitExceededError as e:
            self._count_violation(e)
            # The violating request is billed (its device time landed via
            # the attempt's draft) AND counted under its violation kind —
            # the abuse-control feed services/quotas.py reads: enough of
            # these inside one window and the tenant's NEXT request is
            # quarantined at the door instead of burning a sandbox here.
            self._usage_request(
                usage_tenant, "limit_violation", violation=e.kind
            )
            raise
        except SessionLimitError:
            # Capacity-cap rejections must be visible on dashboards — a
            # burst of 429s with no counter movement reads as "healthy idle".
            self.metrics.executions.inc(outcome="rejected")
            self._usage_request(usage_tenant, "rejected")
            raise
        except (ExecutorError, SandboxSpawnError):
            self.metrics.executions.inc(outcome="infra_error")
            self._usage_request(usage_tenant, "infra_error")
            raise
        finally:
            self._inflight -= 1
            self.quotas.release(quota)
            _auto_profile_var.reset(profile_token)
            _pure_run_var.reset(pure_token)
        await self._memo_finish(memo_key, memo_state, result, auto_profile)
        self._apply_quota_phases(result, quota)
        self._count_execution(
            result,
            session=executor_id is not None,
            usage_tenant=usage_tenant,
            lane=self._lane_hint(chip_count),
            tenant=tenant,
        )
        return result

    # ------------------------------------------------------ result memoization

    def _memo_admission(
        self,
        pure: bool,
        *,
        executor_id: str | None,
        profile: bool,
        source_code: str | None,
        source_file: str | None,
        files: dict[str, str] | None,
        env: dict[str, str] | None,
        chip_count: int | None,
        tenant: str | None,
        limits: dict | None,
    ) -> tuple:
        """Classify one request for the memo check. Returns (key, state):
        state None = memo not in play (purity undeclared, or the kill
        switch — no phases keys, no header, no IO, byte-for-byte pre-memo);
        "bypass" = declared pure but ineligible; "lookup" = eligible.

        Sessions bypass (their whole point is state accumulating across
        requests — the workspace is an input the key can't see); profiler
        runs bypass (the artifact is a side effect keyed outside the
        inputs). Key-derivation failures bypass too: the request's own
        validation owns malformed inputs, never a memo error."""
        if not pure or not self.result_memo.enabled:
            return None, None
        if executor_id is not None or profile or (
            env and "APP_JAX_PROFILE" in env
        ):
            return None, "bypass"
        try:
            lane = self._lane_hint(chip_count)
            # The EFFECTIVE limit box (defaults -> lane -> clamped request
            # override), not the raw override: two requests whose limits
            # resolve identically share output-determining state.
            limits_payload = request_limits(self.config, lane, limits)
            scope = (
                SHARED_SCOPE
                if self.result_memo.shared and _trusted_source_var.get()
                else self.scheduler.normalize_tenant(tenant)
            )
            key = derive_key(
                scope=scope,
                source_code=source_code,
                source_file=source_file,
                files=files,
                env=env,
                limits=limits_payload,
                lane=lane,
                binary_key=self._memo_binary_key,
            )
        except (ValueError, TypeError):
            return None, "bypass"
        return key, "lookup"

    def _memo_hit_result(self, record: dict) -> Result:
        """Build this request's Result from a memo record. The request's
        OWN attribution is zero (no device ran for it); what the recorded
        run measured rides inside the memo block for clients comparing
        cached-vs-live cost."""
        phases: dict[str, float | str] = {
            "chip_seconds": 0.0,
            "device_op_seconds": 0.0,
        }
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            phases["trace_id"] = trace_id
        memo_block: dict = {"state": "hit"}
        recorded_phases = record.get("phases")
        if isinstance(recorded_phases, dict):
            memo_block["recorded"] = recorded_phases
        phases["memo"] = memo_block
        files = record.get("files")
        return Result(
            stdout=str(record.get("stdout", "")),
            stderr=str(record.get("stderr", "")),
            exit_code=int(record.get("exit_code", 0)),
            files=(
                {str(k): str(v) for k, v in files.items()}
                if isinstance(files, dict)
                else {}
            ),
            phases=phases,
            warm=bool(record.get("warm", True)),
            stdout_truncated=bool(record.get("stdout_truncated", False)),
            stderr_truncated=bool(record.get("stderr_truncated", False)),
        )

    def _count_memo_hit(self, result: Result, usage_tenant: str | None) -> None:
        """A memo hit is a LOGICAL request on every surface that counts
        requests — and on none that counts device time: zero chip-seconds
        on the ledger, no perf-baseline sample (nothing was measured; a
        flood of 0-latency hits would poison the drift bands live traffic
        is judged against), no latency-histogram phases."""
        self.result_memo.hits += 1
        self.metrics.result_memo_requests.inc(outcome="hit")
        outcome = "ok" if result.exit_code == 0 else "user_error"
        self.metrics.executions.inc(outcome=outcome)
        self._usage_request(usage_tenant, outcome)

    async def _memo_finish(
        self,
        memo_key,
        memo_state: str | None,
        result: Result,
        auto_profile: str | None,
    ) -> None:
        """Post-run half of the memo protocol: record an eligible miss and
        stamp the request's phases block. Never on the failure path —
        violations and infra faults raised past this point, and a record
        error degrades to an un-memoized success."""
        if memo_state is None:
            return
        recorded = None
        if memo_state == "miss":
            self.result_memo.misses += 1
            if auto_profile is not None:
                # The run grew a control-plane profiler env mid-flight: its
                # key no longer describes what executed.
                recorded = "skipped_profile"
            else:
                recorded = await self._memo_record(memo_key, result)
        block: dict = {"state": memo_state}
        if recorded is not None:
            block["recorded"] = recorded
        result.phases["memo"] = block
        self.metrics.result_memo_requests.inc(outcome=memo_state)

    async def _memo_record(self, memo_key, result: Result) -> str:
        """Admit one completed declared-pure run, when it proved eligible:
        every host echoed the purity declaration and the executor's result
        hash re-derived from the wire fields (result.pure_echo), with
        nothing truncated (a truncation boundary is a limit artifact, not
        program output). Returns the admit outcome string."""
        if memo_key is None:
            return "skipped"
        if result.pure_echo is None:
            return "skipped_echo"
        if result.stdout_truncated or result.stderr_truncated:
            return "skipped_truncated"
        recorded_phases = {
            k: round(float(v), 6)
            for k, v in result.phases.items()
            if isinstance(v, (int, float))
        }
        record = {
            "stdout": result.stdout,
            "stderr": result.stderr,
            "exit_code": result.exit_code,
            "files": dict(result.files),
            "stdout_truncated": result.stdout_truncated,
            "stderr_truncated": result.stderr_truncated,
            "warm": result.warm,
            "phases": recorded_phases,
            # First-write-wins compares THIS: the canonical hash over the
            # merged result (file values are content-addressed object ids,
            # so file bytes are covered transitively).
            "result_sha": result_content_sha(
                result.stdout,
                result.stderr,
                result.exit_code,
                sorted(result.files.values()),
            ),
        }
        try:
            return await self.result_memo.record(memo_key, record)
        except Exception:  # noqa: BLE001 — recording never fails the request
            logger.warning("result memo record failed", exc_info=True)
            return "error"

    @staticmethod
    def _verified_pure_echo(bodies: list) -> str | None:
        """End-to-end check of the executor's purity echo: every host
        acknowledged the declaration, and the primary host's result hash
        re-derives from the very wire fields the Result is built from.
        None — record nothing — on any disagreement, including old
        binaries that don't echo and manifests without content hashes."""
        if not bodies or not all(body.get("pure") is True for body in bodies):
            return None
        primary = bodies[0]
        wire_sha = primary.get("result_sha256")
        if not isinstance(wire_sha, str):
            return None
        entries, has_hashes = parse_files_field(primary.get("files", []))
        if entries and not has_hashes:
            return None
        expected = result_content_sha(
            str(primary.get("stdout", "")),
            str(primary.get("stderr", "")),
            int(primary.get("exit_code", -1)),
            [sha for _rel, sha in entries],
        )
        return wire_sha if wire_sha == expected else None

    def _lane_hint(self, chip_count: int | None) -> int:
        """The lane a request resolves to before validation (the perf
        observer's series key and the auto-profile arm lookup)."""
        if chip_count is None:
            return self.config.default_chip_count
        try:
            return int(chip_count)
        except (TypeError, ValueError):
            return self.config.default_chip_count

    def _maybe_auto_profile(
        self,
        env: dict[str, str] | None,
        chip_count: int | None,
        tenant: str | None,
    ) -> tuple[dict[str, str] | None, str | None]:
        """Consume a pending auto-profile arm for this request's lane, if
        its tenant consents: returns (env with APP_JAX_PROFILE, trigger
        reason) or (env unchanged, None). Client-requested profiling
        (profile=True / explicit env) always wins — that run is the tenant
        profiling itself and bills normally; trusted control-plane runs
        are never auto-profiled (their latencies aren't even recorded)."""
        if not self.perf.enabled or _trusted_source_var.get():
            return env, None
        if env and "APP_JAX_PROFILE" in env:
            return env, None
        try:
            label = self.scheduler.normalize_tenant(tenant)
        except ValueError:
            return env, None  # the request's own validation owns this
        reason = self.perf.take_profile_arm(self._lane_hint(chip_count), label)
        if reason is None:
            return env, None
        return {**(env or {}), "APP_JAX_PROFILE": "1"}, reason

    def _quota_admit(
        self,
        usage_tenant: str | None,
        *,
        chip_count: int | None = None,
        timeout: float | None = None,
    ) -> QuotaVerdict | None:
        """Run the quota gate and keep the rejection observable: a quota
        denial is a rejected request on the dashboards and in the tenant's
        ledger row (requests-by-outcome), exactly like a scheduler shed —
        but it never touches the scheduler. The request's DECLARED cost
        (chip_count x clamped timeout) rides along so the gate can deny a
        predicted overrun before the burn (typed reason=predicted_overrun),
        not after it."""
        try:
            return self.quotas.admit(
                usage_tenant,
                predicted_chip_seconds=self._predicted_chip_seconds(
                    chip_count, timeout
                ),
            )
        except QuotaExceededError:
            self.metrics.executions.inc(outcome="rejected")
            self._usage_request(usage_tenant, "rejected")
            raise

    def _predicted_chip_seconds(
        self, chip_count: int | None, timeout: float | None
    ) -> float:
        """The request's worst-case bill AS DECLARED: chips x the clamped
        timeout the CLIENT declared. A request that declares no timeout
        predicts 0 — the server-side default (60s) is not something the
        client said, and gating on it would permanently deny every tenant
        whose window budget is under chips x 60 regardless of what its
        runs actually cost (those tenants keep the deny-after-the-burn
        semantics). Clamps mirror _validate_request; malformed inputs
        predict 0 (their own validation error owns them, not a quota
        denial)."""
        if timeout is None:
            return 0.0
        try:
            lane = (
                self.config.default_chip_count
                if chip_count is None
                else int(chip_count)
            )
            clamped = min(float(timeout), self.config.max_execution_timeout)
        except (TypeError, ValueError):
            return 0.0
        if clamped <= 0:
            return 0.0
        return max(1, lane) * clamped

    def _apply_quota_phases(
        self, result: Result, quota: QuotaVerdict | None
    ) -> None:
        """Success-path quota exposure (the pacing satellite): a `quota`
        block in Result.phases with the POST-run remaining budget, so a
        well-behaved agent can slow down before ever seeing a 429. Only
        for tenants with a chip-second budget; absent otherwise (and with
        the kill switch, byte-for-byte)."""
        if quota is None:
            return
        # Refresh to the POST-run remaining (this run's bill is already in
        # the ledger), then let the verdict render its one canonical shape.
        self.quotas.refresh_verdict(quota)
        block = quota.phases_block()
        if block is not None:
            result.phases["quota"] = block

    def _usage_tenant(self, tenant: str | None) -> str | None:
        """The normalized tenant name usage accounting records under, or
        None with the metering kill switch on (every `_usage_request` /
        `draft` call then no-ops — pre-metering behavior byte-for-byte).
        Also None for control-plane-authored (trusted) runs: the
        compile-cache pre-warm's JIT compiles are internal warmup work,
        and billing them to the default tenant would contaminate the row
        that bills genuine header-less client requests."""
        if not self.usage.enabled or _trusted_source_var.get():
            return None
        return self.scheduler.normalize_tenant(tenant)

    def _usage_draft(self, tenant: str | None) -> UsageDraft | None:
        """A per-attempt consumption accumulator, or None when this run
        is unmetered (kill switch, or trusted control-plane source)."""
        usage_tenant = self._usage_tenant(tenant)
        if usage_tenant is None:
            return None
        return self.usage.draft(usage_tenant)

    def _usage_request(
        self,
        usage_tenant: str | None,
        outcome: str,
        *,
        violation: str | None = None,
    ) -> None:
        """Count one LOGICAL request against its tenant (resource usage is
        billed per attempt by the drafts; the request itself counts exactly
        once, here at the API surface)."""
        if usage_tenant is None:
            return
        self.usage.add(
            usage_tenant, requests=1, outcome=outcome, violation=violation
        )

    def _count_violation(self, e: LimitExceededError) -> None:
        """Violation bookkeeping shared by both execute surfaces: the
        lane×kind counter, the outcome counter, and — when the violation
        killed the runner (not an in-process guard) — the repeat-offender
        strike on the lane breaker. Enough CONSECUTIVE killed-runner
        violations trip the lane open for one cooldown, so a fleet being
        hammered by violating tenants sheds fast instead of churning
        through kill/respawn cycles at full request rate."""
        self.metrics.limit_violations.inc(
            chip_count=str(e.lane), kind=e.kind
        )
        self.metrics.executions.inc(outcome="limit_violation")
        if not e.continuable:
            breaker = self.breakers.lane(e.lane)
            breaker.record_failure()
            strikes = self._violation_strikes.get(e.lane, 0) + 1
            self._violation_strikes[e.lane] = strikes
            if strikes >= self.config.breaker_failure_threshold:
                breaker.trip(
                    f"{strikes} consecutive limit violations "
                    f"(last: {e.kind})"
                )

    async def _execute_with_retry(
        self,
        source_code: str | None = None,
        *,
        source_file: str | None = None,
        files: dict[str, str] | None = None,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        limits: dict | None = None,
    ) -> Result:
        """Stateless execute with bounded infra retries (ExecutorError only:
        user-code failures are results, capacity/breaker rejections are not
        infrastructure flakes, and limit violations are DETERMINISTIC — the
        same snippet breaches the same budget on any sandbox, so replaying
        one would burn a fresh host per attempt — none of those retry)."""

        def on_retry(failures: int, error: BaseException, delay: float) -> None:
            self.metrics.retry_attempts.inc(operation="execute")
            tracing.add_event(
                "retry",
                operation="execute",
                attempt=failures,
                delay_s=round(delay, 3),
                error=str(error)[:200],
            )

        return await retry_async(
            lambda: self._execute_once(
                source_code,
                source_file=source_file,
                files=files,
                timeout=timeout,
                env=env,
                chip_count=chip_count,
                tenant=tenant,
                priority=priority,
                deadline=deadline,
                limits=limits,
            ),
            self._execute_retry_policy,
            on_retry=on_retry,
        )

    # ------------------------------------------------- batched execution lanes

    def _batch_eligible(
        self,
        source_code: str | None,
        files: dict[str, str] | None,
        env: dict[str, str] | None,
        deadline: float | None,
    ) -> bool:
        """May this request ride a coalesced dispatch? Eligible = stateless
        inline source with no input files, no start deadline, and no
        profiler (the JAX profiler is process-global in the warm runner —
        two jobs cannot trace concurrently). Ineligible requests take the
        EXACT serial path; with the kill switch off, everything does."""
        if self.batcher is None:
            return False
        if _trusted_source_var.get():
            # Control-plane-authored runs (the compile-cache pre-warm) stay
            # serial: coalescing one with tenant jobs would taint the
            # sandbox mid-pre-warm (harvest admits nothing), and the fused
            # dispatch's usage billing keys on the batch's tenant — which
            # an unmetered internal run must not be.
            return False
        if source_code is None or files:
            return False
        if deadline is not None:
            # Deadline admission is a per-request promise about START time;
            # a window-parked job's start is the batch's, not its own. Keep
            # the serial path's exact semantics for deadline traffic.
            return False
        if env and "APP_JAX_PROFILE" in env:
            return False
        return True

    async def _execute_batched(
        self,
        source_code: str,
        *,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        limits: dict | None = None,
    ) -> Result:
        """Park one eligible request in the batching window and await its
        demuxed result. Compatibility keying happens HERE (tenant is part
        of the key by construction — batching never crosses tenants); the
        fused dispatch and per-job fan-out live in `_dispatch_batch`."""
        lane, _files, timeout, limits_payload = self._validate_request(
            source_code, None, None, timeout, chip_count, limits
        )
        if lane < 2 or num_hosts_for(lane, self.config.tpu_chips_per_host) > 1:
            # Coalescing pays on MULTI-chip lanes (idle chips are the waste
            # it recovers); single-chip/CPU lanes keep the serial path
            # byte-for-byte. Multi-HOST slices also stay serial: their
            # hosts rendezvous via jax.distributed, while the fused driver
            # runs on one host's runner (and their jobs are not "small
            # array jobs" anyway).
            return await self._execute_with_retry(
                source_code,
                timeout=timeout,
                env=env,
                chip_count=lane,
                tenant=tenant,
                priority=priority,
                limits=limits,
            )
        # Normalization (and its ValueError on bad client input) happens
        # BEFORE keying, exactly where the serial path validates.
        key = BatchKey(
            lane=lane,
            tenant=self.scheduler.normalize_tenant(tenant),
            priority=self.scheduler.normalize_priority(priority),
            env=freeze_mapping(env),
            limits=tuple(
                sorted(
                    (str(k), float(v))
                    for k, v in (limits_payload or {}).items()
                )
            ),
            timeout=float(timeout),
        )
        span = tracing.current_span()
        job = BatchJob(
            source_code=source_code,
            timeout=timeout,
            trace_id=tracing.current_trace_id(),
            parent_span_id=(
                span.span_id if span is not None and span.recording else None
            ),
            submitted_at=time.perf_counter(),
            # The dispatcher's task doesn't inherit this request's
            # contextvars — the purity declaration rides the job.
            pure=_pure_run_var.get(),
        )
        tracing.add_event(
            "batch.enqueue", lane=lane, pending=self.batcher.pending_jobs(key)
        )
        await self.batcher.submit(key, job)
        return await job.future

    async def _dispatch_batch(self, key: BatchKey, jobs: list[BatchJob]) -> None:
        """One closed batching window: acquire ONE sandbox with a multi-job
        token, run the fused dispatch, and settle every job's future. Any
        batch-level fault (acquisition failure, wire error, old binary,
        runner death, unattributable violation) falls back to the serial
        path per job — no request ever fails *because* it was batched."""
        # Dispatch runs in a batcher task that inherited SOME submitter's
        # trace context; detach so late spans never contaminate that
        # request's exported trace (the _off_request_path discipline).
        tracing.current_span_var.set(None)
        n = len(jobs)
        self.scheduler.observe_batch(key.lane, n, self.config.batch_max_jobs)
        if n < 2:
            # A window that expired under-filled: nothing to fuse, no
            # reason to leave the serial path's exact behavior.
            await self._serial_fallback(key, jobs, reason="underfilled")
            return
        try:
            sandbox = await self._acquire(
                key.lane, tenant=key.tenant, priority=key.priority, jobs=n
            )
        except Exception:
            # Breaker open / capacity timeout / shed: the serial path hits
            # the same admission wall per job and surfaces the standard
            # typed errors (with their standard metrics) to each caller.
            # CancelledError (and other BaseExceptions) propagate instead:
            # a shutdown-cancelled dispatch must fail its jobs' futures
            # (Batcher._run_dispatch does), not restart N serial runs at
            # exactly the moment the service is trying to stop.
            await self._serial_fallback(key, jobs, reason="acquire_failed")
            return
        reusable = False
        settled = False
        try:
            outcomes = await self._run_batch_on_sandbox(sandbox, key, jobs)
            reusable = True
            self.metrics.batch_dispatches.inc(outcome="ok")
            self.metrics.batch_jobs.inc(n, outcome="batched")
            for job, outcome in zip(jobs, outcomes):
                if isinstance(outcome, BaseException):
                    job.fail(outcome)
                else:
                    job.resolve(outcome)
            settled = True
        except LimitExceededError as e:
            # Batch-LEVEL violation (watchdog group kill / post-exec quota):
            # one address space means the breach cannot be pinned on one
            # job here. Dispose-vs-recycle follows the violation's own
            # continuable flag; the serial rerun below gives each job its
            # individual verdict (the real violator gets its 422, its
            # batchmates their clean results).
            reusable = e.continuable
            logger.warning(
                "batched dispatch hit a batch-level %s violation; "
                "re-running %d jobs serially",
                e.kind,
                n,
            )
            self.metrics.batch_dispatches.inc(outcome="violation_fallback")
        except Exception:
            logger.warning(
                "batched dispatch failed; re-running %d jobs serially",
                n,
                exc_info=True,
            )
            self.metrics.batch_dispatches.inc(outcome="error_fallback")
        finally:
            self._release_soon(sandbox, key.lane, reusable)
        if not settled:
            await self._serial_fallback(key, jobs, reason="batch_fault")

    async def _serial_fallback(
        self, key: BatchKey, jobs: list[BatchJob], reason: str
    ) -> None:
        """Run each job through the ordinary serial path and settle its
        future with whatever that path produces — success, typed violation,
        or the standard retryable errors. This is the transparency
        guarantee: a batch partner's fault costs its batchmates only time."""
        if reason != "underfilled":
            logger.info(
                "batch fallback (lane=%d, jobs=%d, reason=%s)",
                key.lane,
                len(jobs),
                reason,
            )
        self.metrics.batch_jobs.inc(len(jobs), outcome=f"serial_{reason}")
        env = dict(key.env) or None
        limits = {k: v for k, v in key.limits} or None

        async def one(job: BatchJob) -> None:
            # gather() wraps each coroutine in its own task (own context
            # copy), so re-asserting the submitter's purity declaration
            # here is job-isolated.
            token = _pure_run_var.set(job.pure)
            try:
                result = await self._execute_with_retry(
                    job.source_code,
                    timeout=job.timeout,
                    env=env,
                    chip_count=key.lane,
                    tenant=key.tenant,
                    priority=key.priority,
                    limits=limits,
                )
            except BaseException as e:
                job.fail(e)
            else:
                job.resolve(result)
            finally:
                _pure_run_var.reset(token)

        await asyncio.gather(*(one(job) for job in jobs))

    async def _run_batch_on_sandbox(
        self, sandbox: Sandbox, key: BatchKey, jobs: list[BatchJob]
    ) -> list:
        """The fused round-trip: POST /execute-batch to the sandbox (which
        stages one workdir per job and runs them as one dispatch spread
        over the lane's device axis), then demux per-job stdout/stderr,
        changed files, violations, and trace spans back to each caller.

        Returns one outcome per job: a Result, or a LimitExceededError for
        a job whose IN-PROCESS guard fired (its batchmates' results stay
        clean). Batch-level faults raise instead — the caller falls back."""
        self._check_lease(sandbox)
        client = self._http_client()
        if self.compile_cache.enabled:
            # Tenant code is about to run: same provenance taint as the
            # serial path (see _run_on_sandbox).
            self._cache_sync(sandbox).taint()
            if self._compile_cache_dir_scope() == "shared":
                self._shared_cache_tainted = True
        base = sandbox.host_urls[0]
        n = len(jobs)
        overall_timeout = max(job.timeout for job in jobs)
        try:
            from ..parallel.mesh import job_device_assignment

            assignment = job_device_assignment(n, sandbox.chip_count or None)
        except Exception:  # noqa: BLE001 — placement is a hint, not a gate
            assignment = [None] * n
        payload: dict = {
            "timeout": overall_timeout,
            "jobs": [
                {
                    "source_code": job.source_code,
                    **({"trace_id": job.trace_id} if job.trace_id else {}),
                    **({"pure": True} if job.pure else {}),
                    **(
                        {"device_index": device}
                        if device is not None
                        else {}
                    ),
                }
                for job, device in zip(jobs, assignment)
            ],
        }
        if self.perf.enabled:
            # Per-job device-memory brackets, same knob as the serial path.
            payload["device_memory"] = True
        if key.env:
            payload["env"] = dict(key.env)
        if key.limits:
            payload["limits"] = {k: v for k, v in key.limits}
        usage_tenant = key.tenant if self.usage.enabled else None
        chips = max(1, sandbox.chip_count or 0)
        exec_start_wall = time.time()
        exec_start = time.perf_counter()
        try:
            body = await self._post_execute_batch(
                client, base, payload, overall_timeout, sandbox
            )
        except ExecutorError as e:
            if usage_tenant is not None and getattr(
                e, "device_may_have_run", True
            ):
                # Wire fault mid-dispatch: the fused run consumed (or is
                # still consuming) real device time — bill the measured
                # wall, like the serial fault path. The serial fallback's
                # reruns bill their own consumption separately (the chips
                # really do run twice). CLEAN REFUSALS are exempt: a 404
                # (old binary) or 409 (no warm runner) answered without
                # running anything — billing wall x chips there would
                # systematically overbill every batch during a rolling
                # upgrade, on top of the serial rerun's real bill.
                wall = max(0.0, time.perf_counter() - exec_start)
                self.usage.add(
                    usage_tenant,
                    chip_seconds=wall * chips,
                    device_op_seconds=wall,
                )
            raise
        exec_seconds = time.perf_counter() - exec_start
        # The fused dispatch's device-op wall, from the executor's own op
        # window — billed to the batch's ONE tenant (tenant is in the
        # BatchKey by construction) BEFORE the verdict checks below, so a
        # batch that violated or aborted still bills the device time it
        # consumed.
        device_op = self._reported_device_op([body], fallback=exec_seconds)
        total_chip_seconds = device_op * chips
        if usage_tenant is not None:
            cc_block = body.get("compile_cache")
            self.usage.add(
                usage_tenant,
                chip_seconds=total_chip_seconds,
                device_op_seconds=device_op,
                compile_cache_recompiles=self._cc_count(cc_block, "misses"),
                compile_cache_new_bytes=self._cc_count(cc_block, "new_bytes"),
            )
        runner_restarted = bool(body.get("runner_restarted"))
        batch_violation = body.get("violation")
        if batch_violation:
            if batch_violation not in VIOLATION_KINDS:
                batch_violation = "unknown"
            raise LimitExceededError(
                f"sandbox resource limit exceeded during a batched dispatch: "
                f"{batch_violation} (sandbox {sandbox.id})",
                kind=batch_violation,
                lane=sandbox.chip_count,
                continuable=not runner_restarted,
            )
        if runner_restarted:
            raise ExecutorError(
                f"sandbox {sandbox.id} warm runner died mid-batch"
            )
        if body.get("timed_out"):
            # The OVERALL batch window timed out — per-job timeouts are only
            # enforceable on the serial path (threads cannot be killed
            # individually), so rerun there for each job's own verdict.
            raise ExecutorError(
                f"sandbox {sandbox.id} batch dispatch timed out"
            )
        results = body.get("results")
        if not isinstance(results, list) or len(results) != n:
            raise ExecutorError(
                f"sandbox {sandbox.id} returned {0 if not isinstance(results, list) else len(results)} "
                f"batch results for {n} jobs"
            )
        if any(not isinstance(entry, dict) for entry in results):
            # Malformed per-job entries are a BATCH-level fault like a short
            # results array: raising here routes through the serial
            # fallback (with its retries), instead of failing one caller
            # with a hard infra error the serial path would have retried.
            raise ExecutorError(
                f"sandbox {sandbox.id} returned a malformed batch entry"
            )
        if any(entry.get("aborted") for entry in results):
            # A job thread never finished (batch-level abort mid-run): its
            # "result" is unusable and its batchmates' are suspect — the
            # serial rerun owns the per-job verdicts.
            raise ExecutorError(
                f"sandbox {sandbox.id} aborted a batch mid-run"
            )
        if body.get("batch_stdout"):
            # fd-level stdout (a subprocess, a C extension writing fd 1)
            # bypasses the per-thread stream demux and lands in the
            # batch-level capture — it cannot be attributed to a job, and
            # silently dropping it would lose output the serial path
            # returns. Rerun serially: every caller gets its exact output,
            # batching costs this window only time.
            raise ExecutorError(
                f"sandbox {sandbox.id} produced un-demuxable batch-level "
                f"stdout ({len(body['batch_stdout'])} bytes)"
            )
        # Apportion the fused run's chip-seconds across its jobs: per-job
        # exec spans give the weights (equal split when any are absent), so
        # the jobs' shares sum EXACTLY to the dispatch's total — a tenant's
        # bill is identical whether its jobs rode the fused or serial path,
        # and per-job attribution never double-bills or loses time.
        shares = self._batch_chip_shares(results)
        stats = TransferStats()
        outcomes = await asyncio.gather(
            *(
                self._demux_batch_job(
                    client,
                    base,
                    sandbox,
                    key,
                    job,
                    entry,
                    index=i,
                    batch_jobs=n,
                    exec_start_wall=exec_start_wall,
                    exec_start_perf=exec_start,
                    exec_seconds=exec_seconds,
                    warm=bool(body.get("warm", False)),
                    stats=stats,
                    chip_seconds_share=(
                        total_chip_seconds * shares[i]
                        if usage_tenant is not None
                        else None
                    ),
                    device_op_share=(
                        device_op * shares[i]
                        if usage_tenant is not None
                        else None
                    ),
                )
                for i, (job, entry) in enumerate(zip(jobs, results))
            )
        )
        stats.emit(self.metrics)
        if usage_tenant is not None:
            # hbm-byte-seconds, fused-path flavor: each job's peak
            # integrated over ITS device-op share, summing to the same
            # bill the jobs would produce serially (path-invariance, the
            # chip-second discipline).
            hbm_byte_seconds = sum(
                self._block_peak_bytes(entry["device_memory"])
                * device_op
                * share
                for entry, share in zip(results, shares)
                if isinstance(entry.get("device_memory"), dict)
            )
            self.usage.add(
                usage_tenant,
                batch_jobs=n,
                download_bytes=stats.download_bytes,
                hbm_byte_seconds=hbm_byte_seconds,
            )
        # A clean fused run ends the lane's consecutive-violation streak,
        # exactly like a clean serial run.
        self._violation_strikes.pop(sandbox.chip_count, None)
        return outcomes

    @staticmethod
    def _batch_chip_shares(results: list) -> list[float]:
        """Per-job fractions of the fused dispatch's chip-seconds. Weights
        are the per-job exec spans the demux already carries
        (device_op_seconds / duration_s); when ANY job's span is absent the
        whole batch falls back to an equal split — mixing measured weights
        with invented ones would silently skew every share. Fractions sum
        to 1.0 by construction."""
        n = len(results)
        weights: list[float] = []
        for entry in results:
            value = entry.get("device_op_seconds", entry.get("duration_s"))
            if isinstance(value, (int, float)) and value > 0:
                weights.append(float(value))
            else:
                weights = []
                break
        if len(weights) != n or not sum(weights):
            return [1.0 / n] * n
        total = sum(weights)
        return [w / total for w in weights]

    async def _post_execute_batch(
        self,
        client: httpx.AsyncClient,
        base: str,
        payload: dict,
        timeout: float,
        sandbox: Sandbox,
    ) -> dict:
        """The /execute-batch wire hop (split out so tests can fake the
        sandbox exactly like `_post_execute`)."""
        try:
            resp = await client.post(
                f"{base}/execute-batch",
                json=payload,
                headers=self._wire_headers(sandbox),
                timeout=httpx.Timeout(timeout + 30.0),
            )
        except httpx.HTTPError as e:
            raise ExecutorError(
                f"sandbox {sandbox.id} ({base}) unreachable: {e}"
            )
        # 409 on this route ALSO means "no warm runner" (serial-fallback
        # refusal); only the typed stale_lease body raises the lease error.
        self._raise_if_stale_lease(resp, sandbox)
        if resp.status_code != 200:
            # 404 = old binary without the route, 409 = no warm runner:
            # either way the serial path is the answer. The server
            # ANSWERED with a refusal — nothing ran on the device, so
            # usage billing must not charge wall time for this hop
            # (device_may_have_run gates the fault-billing path).
            error = ExecutorError(
                f"sandbox {sandbox.id} ({base}) /execute-batch -> "
                f"{resp.status_code}: {resp.text[:300]}"
            )
            error.device_may_have_run = False
            raise error
        try:
            return resp.json()
        except ValueError as e:
            raise ExecutorError(
                f"sandbox {sandbox.id} ({base}) returned malformed JSON: {e}"
            )

    async def _demux_batch_job(
        self,
        client: httpx.AsyncClient,
        base: str,
        sandbox: Sandbox,
        key: BatchKey,
        job: BatchJob,
        entry,
        *,
        index: int,
        batch_jobs: int,
        exec_start_wall: float,
        exec_seconds: float,
        warm: bool,
        stats: TransferStats,
        exec_start_perf: float | None = None,
        chip_seconds_share: float | None = None,
        device_op_share: float | None = None,
    ):
        """One job's slice of the batch response → its Result (changed
        files downloaded from its private workdir, hash-negotiated like any
        download) or its typed in-process violation. Also grafts the job's
        sandbox timing into the ORIGINATING request's trace. Entries are
        dict-validated by the caller (a malformed one is a batch-level
        fault, not one job's)."""
        duration = entry.get("duration_s")
        if job.trace_id is not None and job.parent_span_id is not None:
            offset = entry.get("start_offset_s")
            self.tracer.record_span(
                "sandbox.batch_job",
                trace_id=job.trace_id,
                parent_id=job.parent_span_id,
                start_unix=exec_start_wall
                + (float(offset) if isinstance(offset, (int, float)) else 0.0),
                duration_s=(
                    float(duration)
                    if isinstance(duration, (int, float))
                    else exec_seconds
                ),
                attributes={
                    "host": base,
                    "batch_index": index,
                    "batch_jobs": batch_jobs,
                },
            )
        violation = entry.get("violation")
        if violation and isinstance(violation, str):
            if violation not in VIOLATION_KINDS:
                logger.warning(
                    "sandbox %s reported unknown batch violation kind %.40r",
                    sandbox.id,
                    violation,
                )
                violation = "unknown"
            stderr_tail = str(entry.get("stderr", ""))[-500:]
            return LimitExceededError(
                f"sandbox resource limit exceeded: {violation} "
                f"(sandbox {sandbox.id}, batched); {stderr_tail}".rstrip("; "),
                kind=violation,
                lane=sandbox.chip_count,
                # The in-process guard fired inside ONE job's thread; the
                # runner (and its batchmates) survived.
                continuable=True,
            )
        workdir = entry.get("workdir")
        merged_files: dict[str, str] = {}
        if isinstance(workdir, str) and workdir:
            entries, _has_hashes = parse_files_field(entry.get("files", []))
            fetched = await asyncio.gather(
                *(
                    self._fetch_changed(
                        client,
                        base,
                        f"{workdir}/{rel}",
                        sha if self._transfer_state(sandbox).enabled else None,
                        stats,
                    )
                    for rel, sha in entries
                )
            )
            for (full_rel, object_id), (rel, _sha) in zip(fetched, entries):
                # Demux contract: the caller sees ITS files at the paths
                # its code wrote them, not the batch's staging prefix.
                merged_files[f"/workspace/{rel}"] = object_id
        phases: dict[str, float | str] = {
            "exec": (
                float(duration)
                if isinstance(duration, (int, float))
                else exec_seconds
            ),
            "batch_jobs": float(batch_jobs),
            "batch_index": float(index),
        }
        if exec_start_perf is not None and job.submitted_at:
            # The job's real pre-exec wait: batching window + scheduler
            # queue — the fused path's analogue of the serial queue_wait
            # phase (a latency; it rides the phase_seconds histogram).
            phases["queue_wait"] = round(
                max(0.0, exec_start_perf - job.submitted_at), 6
            )
        if chip_seconds_share is not None:
            # This job's apportioned slice of the fused dispatch's
            # chip-seconds (per-job exec spans weight the split): summed
            # over the batch these equal the dispatch's total exactly.
            phases["chip_seconds"] = round(chip_seconds_share, 6)
        if device_op_share is not None:
            phases["device_op_seconds"] = round(device_op_share, 6)
        # Per-job device-memory block (best-effort under concurrent
        # batchmates — one address space): same phase keys as the serial
        # path, so a client reads one shape either way.
        mem_phases, _peak = self._device_memory_phases([entry])
        phases.update(mem_phases)
        if job.trace_id is not None:
            phases["trace_id"] = job.trace_id
        return Result(
            stdout=str(entry.get("stdout", "")),
            stderr=str(entry.get("stderr", "")),
            exit_code=int(entry.get("exit_code", -1)),
            files=merged_files,
            phases=phases,
            warm=warm,
            stdout_truncated=bool(entry.get("stdout_truncated", False)),
            stderr_truncated=bool(entry.get("stderr_truncated", False)),
            # Per-job purity echo: the entry hashes ITS OWN demuxed
            # streams/files, so a batchmate's output can never leak into a
            # recorded result unnoticed.
            pure_echo=(
                self._verified_pure_echo([entry]) if job.pure else None
            ),
        )

    async def _execute_once(
        self,
        source_code: str | None = None,
        *,
        source_file: str | None = None,
        files: dict[str, str] | None = None,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        limits: dict | None = None,
        emit=None,
    ) -> Result:
        lane, files, timeout, limits_payload = self._validate_request(
            source_code, source_file, files, timeout, chip_count, limits
        )
        timer = PhaseTimer()
        # One draft per ATTEMPT (the retry ladder re-enters here): a failed
        # attempt consumed real device time and is billed; the logical
        # request is counted once, at the API surface.
        usage = self._usage_draft(tenant)

        with timer.phase("queue_wait"):
            sandbox = await self._acquire(
                lane, tenant=tenant, priority=priority, deadline=deadline
            )
        reusable = False
        try:
            result, _continuable = await self._run_on_sandbox(
                sandbox, source_code, source_file, files, timeout, env, timer,
                limits=limits_payload, emit=emit, usage=usage,
            )
            # The request completed (user errors included). Whether the
            # sandbox is actually safe to recycle is the server's call —
            # /reset refuses (409) when its runner was killed by a timeout
            # or died — so only infra failures (exceptions before this
            # point) hard-disqualify reuse here.
            reusable = True
            return result
        except LimitExceededError as e:
            # Repeat-offender path: a violation that killed the runner makes
            # the host non-reusable (disposed + lane refilled); an
            # in-process guard left it scrubbable, so it recycles normally.
            reusable = e.continuable
            raise
        finally:
            # Attribution commits on EVERY exit — success, violation, or
            # fault: a request that fails after consuming device time is
            # still billed (the draft holds whatever the attempt measured).
            self.usage.commit(usage)
            # Sandbox release off the hot path: recycle the warm device
            # process back into the pool (generation turnover via /reset),
            # or dispose it when it can't be safely reused.
            self._release_soon(sandbox, lane, reusable)

    def _validate_request(
        self,
        source_code: str | None,
        source_file: str | None,
        files: dict[str, str] | None,
        timeout: float | None,
        chip_count: int | None,
        limits: dict | None = None,
    ) -> tuple[int, dict[str, str], float, dict | None]:
        if (source_code is None) == (source_file is None):
            raise ValueError("exactly one of source_code/source_file is required")
        files = files or {}
        lane = self.config.default_chip_count if chip_count is None else chip_count
        # Fail a non-tiling chip_count here, before any pool machinery runs
        # (surfaces as an invalid-argument error, not a spawn failure).
        num_hosts_for(lane, self.config.tpu_chips_per_host)
        timeout = min(
            timeout or self.config.default_execution_timeout,
            self.config.max_execution_timeout,
        )
        # Resource budget: defaults -> lane -> request override, clamped by
        # the server caps; malformed overrides fail here as client errors.
        limits_payload = request_limits(self.config, lane, limits)
        return lane, files, timeout, limits_payload

    async def _run_on_sandbox(
        self,
        sandbox: Sandbox,
        source_code: str | None,
        source_file: str | None,
        files: dict[str, str],
        timeout: float,
        env: dict[str, str] | None,
        timer: PhaseTimer,
        limits: dict | None = None,
        emit=None,
        usage: UsageDraft | None = None,
    ) -> tuple[Result, bool]:
        """The sandbox round-trip: upload inputs, fan /execute out to every
        host, download changed files. Returns (result, continuable) —
        continuable is False when a host's warm runner was killed (timeout)
        or crashed, i.e. any in-process state is gone and a session must not
        keep using the sandbox.

        A host reporting a typed `violation` raises LimitExceededError
        BEFORE the download phase: the bytes a disk-filler left behind are
        exactly what must not be shipped into content-addressed storage.

        With `emit` (an async callback), host 0 runs via /execute/stream and
        stdout/stderr chunks are emitted as the code produces them; the final
        Result is identical either way (the stream's last event carries the
        full response body). Peers of a multi-host slice never stream — host
        0 is the coordinator and, per JAX convention, does the singular side
        effects worth watching live."""
        # Lease gate before ANY wire traffic: a fence that landed while
        # this request held the sandbox refuses here, cleanly, instead of
        # dispatching into (or racing) the wedged device plane.
        self._check_lease(sandbox)
        client = self._http_client()
        if self.compile_cache.enabled and not _trusted_source_var.get():
            # Tenant code is about to run (or try to): this sandbox's cache
            # dir is attacker-writable from here on, so its compile-cache
            # harvest eligibility is revoked for the sandbox's lifetime —
            # the cache dir survives /reset, so the taint must too. Set
            # BEFORE any tenant byte runs, so a harvest racing this request
            # can never observe untainted state after a tenant write.
            self._cache_sync(sandbox).taint()
            if self._compile_cache_dir_scope() == "shared":
                # Every sandbox shares this one's cache dir: the write
                # surface is control-plane-wide, so the taint is too.
                self._shared_cache_tainted = True
        # A multi-host slice is one sandbox with an executor per host:
        # inputs go to every host, /execute fires on every host (the
        # hosts rendezvous via their pre-established jax.distributed
        # mesh), and outputs merge with host-0 precedence.
        hosts = sandbox.host_urls
        transfer = self._transfer_state(sandbox)
        stats = TransferStats()
        if usage is not None:
            # The chip multiplier: the sandbox's actual topology (a lane-0
            # "whatever the sandbox has" request bills what it really
            # held; CPU sandboxes bill device-op seconds x 1).
            usage.chips = max(1, sandbox.chip_count or 0)
        with timer.phase("upload"):
            with self.tracer.span("transfer.upload") as upload_span:
                try:
                    await self._upload_inputs(
                        client, hosts, transfer, files, stats
                    )
                except LimitExceededError as e:
                    # The executor's PUT quota fired (413): enrich with the
                    # lane and account it like an exec-phase violation.
                    e.lane = sandbox.chip_count
                    tracing.add_event(
                        "limit.violation", kind=e.kind, lane=e.lane,
                        phase="upload",
                    )
                    raise
                upload_span.set_attribute("bytes_moved", stats.upload_bytes)
                upload_span.set_attribute(
                    "bytes_skipped", stats.upload_skipped_bytes
                )
                upload_span.set_attribute("files_moved", stats.upload_files)
                upload_span.set_attribute(
                    "files_skipped", stats.upload_skipped_files
                )
        with timer.phase("exec"):
            payload: dict = {"timeout": timeout}
            if self.perf.enabled:
                # Ask the sandbox for the device-memory bracket (live/peak
                # buffer bytes + runner RSS around the run). Only when the
                # perf plane is live — the kill switch keeps the wire
                # payload, and the runner's sampling cost, byte-for-byte
                # what it is today.
                payload["device_memory"] = True
            if _pure_run_var.get():
                # Purity declaration (result-memo miss in flight): the
                # executor echoes it with a result hash the record path
                # verifies end-to-end (see _verified_pure_echo).
                payload["pure"] = True
            if env:
                payload["env"] = env
            if limits:
                payload["limits"] = limits
            if source_code is not None:
                payload["source_code"] = source_code
            else:
                payload["source_file"] = source_file
            if usage is not None:
                usage.upload_bytes += stats.upload_bytes
            exec_started = time.perf_counter()
            bodies = await asyncio.gather(
                *(
                    self._call_host(
                        client, index, base, payload, timeout, sandbox, emit
                    )
                    for index, base in enumerate(hosts)
                ),
                # Let every host finish before surfacing a failure — a
                # half-cancelled slice group would leak in-flight
                # requests into the dispose path.
                return_exceptions=True,
            )
            failure = next(
                (b for b in bodies if isinstance(b, BaseException)), None
            )
            if failure is not None:
                if usage is not None and getattr(
                    failure, "device_may_have_run", True
                ):
                    # Wire fault mid-exec: the executor's own op clock is
                    # unreachable, but the device very likely ran (or is
                    # still running) the whole window — bill the measured
                    # exec wall, the best evidence available. A request is
                    # never free just because it faulted. Clean refusals
                    # (non-200: the server answered without running) are
                    # exempt — see _post_execute.
                    usage.device_op_seconds += max(
                        0.0, time.perf_counter() - exec_started
                    )
                raise failure
            # The executor's OWN op window (the device_op_seconds wire
            # field; duration_s on an older binary) — NOT control-plane
            # wall, which includes queueing/transfer. A multi-host slice's
            # hosts run one op in parallel: the op wall is the slowest
            # host's. Held in a local because both the chip-second bill
            # and the hbm-byte-second integral below read it.
            op_wall = self._reported_device_op(
                bodies,
                fallback=max(0.0, time.perf_counter() - exec_started),
            )
            if usage is not None:
                # Observed BEFORE the violation check below, so a violating
                # request still bills the device time it consumed.
                usage.device_op_seconds += op_wall
            self._raise_on_violation(sandbox, hosts, bodies)
        with timer.phase("download"):
            with self.tracer.span("transfer.download") as download_span:
                merged_files = await self._download_changed(
                    client, hosts, transfer, bodies, stats
                )
                download_span.set_attribute("bytes_moved", stats.download_bytes)
                download_span.set_attribute(
                    "bytes_skipped", stats.download_skipped_bytes
                )
                download_span.set_attribute(
                    "files_moved", stats.download_files
                )
                download_span.set_attribute(
                    "files_skipped", stats.download_skipped_files
                )
        primary = bodies[0]
        stderr = primary.get("stderr", "")
        exit_code = int(primary.get("exit_code", -1))
        for host_index, body in enumerate(bodies[1:], start=1):
            host_exit = int(body.get("exit_code", -1))
            if host_exit != 0 and exit_code == 0:
                exit_code = host_exit
            if host_exit != 0 and body.get("stderr"):
                stderr += ("\n" if stderr else "") + (
                    f"[host {host_index}] {body['stderr']}"
                )
        continuable = not any(bool(b.get("runner_restarted")) for b in bodies)
        if not continuable:
            # A runner was killed mid-request: stray user processes may have
            # mutated the workspace after the post-execute scan, so the
            # cached manifests are no longer trustworthy. Forget them; the
            # next upload phase resyncs from GET /workspace-manifest.
            transfer.invalidate()
        stats.emit(self.metrics)
        phases = {**timer.as_dict(), **stats.as_phases()}
        phases.update(self._compile_cache_phases(sandbox, bodies))
        # Device-memory accounting: the hosts' wire blocks folded into
        # phases (peak_hbm_bytes / live_buffer_bytes_delta — non-latency
        # keys, excluded from the histogram by the allowlist) and, below,
        # integrated over the op wall into the tenant's ledger.
        mem_phases, peak_hbm = self._device_memory_phases(bodies)
        phases.update(mem_phases)
        # Auto-profile harvest: a control-plane-armed profiler run's
        # profile.zip moves OUT of the tenant's files into the profile
        # store — the tenant neither asked for nor receives it, and (the
        # PR 9 trusted-run rule) must not be billed its transfer.
        auto_profile = _auto_profile_var.get()
        harvested_bytes = 0
        if auto_profile is not None:
            harvested_bytes = await self._harvest_profile(
                merged_files,
                sandbox,
                auto_profile,
                tenant=usage.tenant if usage is not None else None,
            )
        if usage is not None:
            usage.hbm_byte_seconds += max(0.0, peak_hbm) * op_wall
            usage.download_bytes += max(
                0, stats.download_bytes - harvested_bytes
            )
            usage.compile_cache_recompiles += float(
                phases.get("compile_cache_misses", 0.0)
            )
            usage.compile_cache_new_bytes += float(
                phases.get("compile_cache_new_bytes", 0.0)
            )
            # Per-request attribution fields: what THIS run cost, as
            # billed. Not latencies — the phase_seconds allowlist keeps
            # them out of the latency histogram by construction.
            phases["device_op_seconds"] = round(usage.device_op_seconds, 6)
            phases["chip_seconds"] = round(usage.chip_seconds, 6)
        # Correlate the response with its trace: clients quote this id at
        # GET /traces/{trace_id} (it also rides the X-Trace-Id header and
        # gRPC trailing metadata). A string among the float phase values —
        # consumers that iterate phases numerically skip non-numbers.
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            phases["trace_id"] = trace_id
        # A clean run ends the lane's consecutive-violation streak (the
        # repeat-offender trip targets storms, not a mixed workload).
        self._violation_strikes.pop(sandbox.chip_count, None)
        result = Result(
            stdout=primary.get("stdout", ""),
            stderr=stderr,
            exit_code=exit_code,
            files=merged_files,
            phases=phases,
            warm=bool(primary.get("warm", False)),
            stdout_truncated=bool(primary.get("stdout_truncated", False)),
            stderr_truncated=any(
                bool(b.get("stderr_truncated", False)) for b in bodies
            ),
            pure_echo=(
                self._verified_pure_echo(bodies)
                if _pure_run_var.get()
                else None
            ),
        )
        return result, continuable

    @staticmethod
    def _reported_device_op(bodies: list, fallback: float = 0.0) -> float:
        """The device-op wall the executor itself measured for this
        request: `device_op_seconds` from the wire (the op window around
        the runner round-trip / cold subprocess), `duration_s` from an
        older binary, control-plane exec wall only when neither answered.
        Hosts of one slice run the op in parallel — the wall is the max."""
        values = [
            body.get("device_op_seconds", body.get("duration_s"))
            for body in bodies
            if isinstance(body, dict)
        ]
        numbers = [
            float(v) for v in values if isinstance(v, (int, float)) and v >= 0
        ]
        return max(numbers) if numbers else max(0.0, fallback)

    PROFILE_ARTIFACT = "/workspace/profile.zip"

    @staticmethod
    def _block_peak_bytes(block: dict) -> float:
        """One host's per-request peak device-buffer bytes from its
        device_memory wire block. When the allocator's process-lifetime
        peak MOVED during the run, that new high-water IS this request's
        peak; otherwise the request ran under an older high-water and the
        honest per-request figure is what it actually held (the larger of
        the live samples bracketing the run — the CPU/live_arrays path,
        which has no allocator peak at all, always lands here). -1 wire
        values mean "unavailable" and never poison the max."""

        def num(key: str) -> float:
            value = block.get(key)
            return float(value) if isinstance(value, (int, float)) else -1.0

        live = [
            v
            for v in (num("live_bytes_before"), num("live_bytes_after"))
            if v >= 0
        ]
        base = max(live) if live else 0.0
        peak_before = num("peak_bytes_before")
        peak_after = num("peak_bytes_after")
        if peak_after >= 0 and peak_after > peak_before >= 0:
            return max(base, peak_after)
        return base

    def _device_memory_phases(
        self, bodies: list[dict]
    ) -> tuple[dict[str, float], float]:
        """Fold the hosts' device_memory wire blocks into Result.phases
        fields; returns (phases, peak_hbm_bytes). A multi-host slice sums
        peaks and live deltas across hosts (the slice's total footprint)
        and reports the largest runner RSS. Returns ({}, 0) when no host
        reported (old binary, cold subprocess, plane disabled)."""
        if not self.perf.enabled:
            return {}, 0.0
        peak = delta = 0.0
        rss = -1.0
        seen = False
        for body in bodies:
            block = body.get("device_memory")
            if not isinstance(block, dict):
                continue
            seen = True
            peak += self._block_peak_bytes(block)
            before = block.get("live_bytes_before")
            after = block.get("live_bytes_after")
            if (
                isinstance(before, (int, float))
                and isinstance(after, (int, float))
                and before >= 0
                and after >= 0
            ):
                delta += float(after) - float(before)
            block_rss = block.get("rss_bytes")
            if isinstance(block_rss, (int, float)) and block_rss > rss:
                rss = float(block_rss)
        if not seen:
            return {}, 0.0
        phases: dict[str, float] = {
            "peak_hbm_bytes": round(peak, 1),
            "live_buffer_bytes_delta": round(delta, 1),
        }
        if rss >= 0:
            phases["runner_rss_bytes"] = round(rss, 1)
        return phases, peak

    async def _harvest_profile(
        self,
        merged_files: dict[str, str],
        sandbox: Sandbox,
        reason: str,
        *,
        tenant: str | None,
    ) -> int:
        """Move an auto-captured profile.zip from the request's changed
        files into the profile store (content-addressed, trace-id
        cross-linked). Returns the artifact's byte size so the caller can
        exempt the harvest from the tenant's transfer bill. Best-effort:
        a failed harvest logs and bills nothing extra — the artifact
        simply stays in the tenant's files like a client-requested
        profile."""
        object_id = merged_files.get(self.PROFILE_ARTIFACT)
        if object_id is None:
            return 0
        try:
            data = await self.storage.read(object_id)
        except (StorageObjectNotFound, OSError):
            logger.warning(
                "auto-profile artifact %s unreadable; leaving it in the "
                "request's files",
                object_id,
            )
            return 0
        profile_id = self.perf.note_profile_captured(
            data,
            lane=sandbox.chip_count,
            reason=reason,
            tenant=tenant,
            trace_id=tracing.current_trace_id(),
        )
        if profile_id is None:
            # The store couldn't make the artifact durable (full/unwritable
            # volume): leave the ONLY copy in the request's files — billed
            # and returned like a client-requested profile — instead of
            # destroying the regression evidence.
            logger.warning(
                "auto-profile store rejected the artifact; leaving it in "
                "the request's files (billed normally)"
            )
            return 0
        del merged_files[self.PROFILE_ARTIFACT]
        tracing.add_event(
            "perf.profile_harvested", reason=reason, bytes=len(data)
        )
        return len(data)

    @staticmethod
    def _cc_count(block, key: str) -> int:
        """One reading of the executor's `compile_cache` response block:
        non-dict blocks and non-numeric/negative values read as 0. ONE
        implementation for the serial and batch paths — a wire-format
        tweak parsed differently per path would skew batch billing
        relative to serial, breaking the bill's path-invariance."""
        if not isinstance(block, dict):
            return 0
        value = block.get(key)
        return int(value) if isinstance(value, (int, float)) and value > 0 else 0

    def _compile_cache_phases(
        self, sandbox: Sandbox, bodies: list[dict]
    ) -> dict[str, float]:
        """Per-request compile-cache observability: the hosts' hit/miss and
        new-entry counters summed into Result.phases, a trace event on the
        execute span, and the hit/miss outcome counters. A request that
        popped a freshly seeded sandbox also reports what seeding it cost."""
        if not self.compile_cache.enabled:
            return {}
        hits = misses = new_entries = new_bytes = 0
        seen = False
        for body in bodies:
            block = body.get("compile_cache")
            if not isinstance(block, dict):
                continue
            seen = True
            hits += self._cc_count(block, "hits")
            misses += self._cc_count(block, "misses")
            new_entries += self._cc_count(block, "new_entries")
            new_bytes += self._cc_count(block, "new_bytes")
        phases: dict[str, float] = {}
        if seen:
            phases["compile_cache_hits"] = float(hits)
            phases["compile_cache_misses"] = float(misses)
            phases["compile_cache_new_bytes"] = float(new_bytes)
            if hits:
                self.metrics.compile_cache_kernels.inc(hits, outcome="hit")
            if misses:
                self.metrics.compile_cache_kernels.inc(misses, outcome="miss")
            if hits or misses or new_entries:
                tracing.add_event(
                    "compile_cache",
                    hits=hits,
                    misses=misses,
                    new_entries=new_entries,
                    new_bytes=new_bytes,
                )
        sync = sandbox.meta.get("compile_cache")
        if (
            isinstance(sync, SandboxCacheSync)
            and sync.pending_seed_bytes is not None
        ):
            phases["compile_cache_seeded_bytes"] = float(
                sync.pending_seed_bytes
            )
            sync.pending_seed_bytes = None
        return phases

    def _raise_on_violation(
        self, sandbox: Sandbox, hosts: list[str], bodies: list[dict]
    ) -> None:
        """Map a host-reported typed `violation` into LimitExceededError.
        `continuable` mirrors the executor's runner_restarted: an in-process
        guard (runner alive) leaves the host recyclable; a watchdog kill
        marks it for disposal and a lane-breaker strike."""
        for base, body in zip(hosts, bodies):
            kind = body.get("violation")
            if not kind or not isinstance(kind, str):
                continue
            if kind not in VIOLATION_KINDS:
                # The kind is a metrics label and a wire contract: an
                # out-of-contract executor (version skew, compromise) must
                # not mint unbounded label cardinality or leak junk to
                # clients.
                logger.warning(
                    "sandbox %s reported unknown violation kind %.40r",
                    sandbox.id,
                    kind,
                )
                kind = "unknown"
            continuable = not bool(body.get("runner_restarted"))
            tracing.add_event(
                "limit.violation",
                kind=kind,
                lane=sandbox.chip_count,
                host=base,
                continuable=continuable,
            )
            stderr_tail = str(body.get("stderr", ""))[-500:]
            raise LimitExceededError(
                f"sandbox resource limit exceeded: {kind} "
                f"(sandbox {sandbox.id}); {stderr_tail}".rstrip("; "),
                kind=kind,
                lane=sandbox.chip_count,
                continuable=continuable,
            )

    async def execute_stream(
        self,
        source_code: str | None = None,
        *,
        source_file: str | None = None,
        files: dict[str, str] | None = None,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        profile: bool = False,
        executor_id: str | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        limits: dict | None = None,
        pure: bool = False,
    ):
        """Streaming variant of execute(): an async generator yielding
        ``{"stream": "stdout"|"stderr", "data": str}`` events while the code
        runs (host 0 of the sandbox), then one ``{"result": Result}`` event.

        Infra failures are NOT retried — output already streamed to the
        client cannot be un-streamed, so a silent retry would duplicate it;
        the error surfaces and the client decides (same policy as sessions).

        A declared-pure (`pure=True`) hit serves the final result event
        directly — the full stdout/stderr ride it, exactly as a live
        stream's final event carries them; there is simply nothing to
        stream incrementally because nothing runs.
        """
        env, executor_id = self._normalize_request(env, profile, executor_id)
        usage_tenant = self._usage_tenant(tenant)
        self._check_admission_open()
        # Same quota gate as execute(): a denial surfaces before the first
        # stream event (the HTTP layer still returns a clean 429).
        quota = self._quota_admit(
            usage_tenant, chip_count=chip_count, timeout=timeout
        )
        # Result-memo admission, like execute(): after the quota gate,
        # before the profile arm.
        memo_key, memo_state = self._memo_admission(
            pure,
            executor_id=executor_id,
            profile=profile,
            source_code=source_code,
            source_file=source_file,
            files=files,
            env=env,
            chip_count=chip_count,
            tenant=tenant,
            limits=limits,
        )
        if memo_state == "lookup":
            record = await self.result_memo.lookup(memo_key)
            if record is not None:
                try:
                    result = self._memo_hit_result(record)
                    self._apply_quota_phases(result, quota)
                    self._count_memo_hit(result, usage_tenant)
                finally:
                    self.quotas.release(quota)
                yield {"result": result}
                return
            memo_state = "miss"
        # Auto-profile arming, like execute() (post-admission). Set BEFORE
        # the run task is created: create_task snapshots the contextvars,
        # which is how the marker reaches the pipeline inside run().
        env, auto_profile = self._maybe_auto_profile(env, chip_count, tenant)
        profile_token = _auto_profile_var.set(auto_profile)
        pure_token = _pure_run_var.set(memo_state == "miss")
        queue: asyncio.Queue = asyncio.Queue()
        done = object()

        async def emit(event: dict) -> None:
            queue.put_nowait(event)

        async def run() -> Result:
            try:
                if executor_id is not None:
                    return await self._execute_in_session(
                        executor_id,
                        source_code,
                        source_file=source_file,
                        files=files,
                        timeout=timeout,
                        env=env,
                        chip_count=chip_count,
                        tenant=tenant,
                        priority=priority,
                        deadline=deadline,
                        limits=limits,
                        emit=emit,
                    )
                return await self._execute_once(
                    source_code,
                    source_file=source_file,
                    files=files,
                    timeout=timeout,
                    env=env,
                    chip_count=chip_count,
                    tenant=tenant,
                    priority=priority,
                    deadline=deadline,
                    limits=limits,
                    emit=emit,
                )
            finally:
                queue.put_nowait(done)

        self._inflight += 1
        task = asyncio.create_task(run())
        try:
            while True:
                event = await queue.get()
                if event is done:
                    break
                yield event
            try:
                result = await task
            except CircuitOpenError as e:
                self.metrics.breaker_rejections.inc(chip_count=str(e.lane))
                self.metrics.executions.inc(outcome="rejected")
                self._usage_request(usage_tenant, "rejected")
                raise
            except LimitExceededError as e:
                self._count_violation(e)
                self._usage_request(
                    usage_tenant, "limit_violation", violation=e.kind
                )
                raise
            except SessionLimitError:
                self.metrics.executions.inc(outcome="rejected")
                self._usage_request(usage_tenant, "rejected")
                raise
            except (ExecutorError, SandboxSpawnError):
                self.metrics.executions.inc(outcome="infra_error")
                self._usage_request(usage_tenant, "infra_error")
                raise
        except BaseException:
            task.cancel()
            # The run task owns sandbox/session cleanup; let it finish it.
            await asyncio.gather(task, return_exceptions=True)
            raise
        finally:
            self._inflight -= 1
            self.quotas.release(quota)
            _auto_profile_var.reset(profile_token)
            _pure_run_var.reset(pure_token)
        await self._memo_finish(memo_key, memo_state, result, auto_profile)
        self._apply_quota_phases(result, quota)
        self._count_execution(
            result,
            session=executor_id is not None,
            usage_tenant=usage_tenant,
            lane=self._lane_hint(chip_count),
            tenant=tenant,
        )
        yield {"result": result}

    def _normalize_request(
        self,
        env: dict[str, str] | None,
        profile: bool,
        executor_id: str | None,
    ) -> tuple[dict[str, str] | None, str | None]:
        """Request normalization shared by execute() and execute_stream():
        profile flag → sandbox env; "" executor_id → stateless (proto3
        default); sessions disabled → executor_id accepted and IGNORED
        (reference-parity mode: the -fs reference carried the field but
        ignored it, and clients threading opaque per-request ids under that
        contract must not open one throwaway session per request)."""
        if profile:
            env = {**(env or {}), "APP_JAX_PROFILE": "1"}
        if executor_id == "":
            executor_id = None
        if executor_id is not None and self.config.executor_session_max <= 0:
            executor_id = None
        return env, executor_id

    def _count_execution(
        self,
        result: Result,
        *,
        session: bool,
        usage_tenant: str | None = None,
        lane: int | None = None,
        tenant: str | None = None,
    ) -> None:
        outcome = "ok" if result.exit_code == 0 else "user_error"
        self.metrics.executions.inc(outcome=outcome)
        self._usage_request(usage_tenant, outcome)
        if result.warm:
            self.metrics.warm_hits.inc()
        if session:
            self.metrics.session_executions.inc()
        if (
            lane is not None
            and self.perf.enabled
            and not _trusted_source_var.get()
        ):
            # The perf plane's ONE record point: every LOGICAL request
            # (serial, session, or batched — batch demux fills the same
            # phase keys) feeds the lane×phase baselines and the tenant
            # series. Trusted pre-warm runs stay out: control-plane warmup
            # latency must not poison the baselines tenant traffic is
            # judged against. Independent of the metering kill switch —
            # drift detection is not billing.
            try:
                perf_tenant = self.scheduler.normalize_tenant(tenant)
            except ValueError:
                perf_tenant = None
            self.perf.record_request(lane, result.phases, tenant=perf_tenant)
        for phase, seconds in result.phases.items():
            # ALLOWLIST, not exclusion: phases also carries byte counts,
            # compile-cache/batch coordinates, the trace id, and the usage
            # attribution fields (chip_seconds/device_op_seconds) — PRs 6,
            # 7, and 8 each re-fixed a new non-latency key polluting this
            # histogram; now a key must be a known latency phase to land.
            if phase not in LATENCY_PHASES or not isinstance(
                seconds, (int, float)
            ):
                continue
            self.metrics.phase_seconds.observe(seconds, phase=phase)

    # --------------------------------------------------------------- sessions

    async def _execute_in_session(
        self,
        executor_id: str,
        source_code: str | None = None,
        *,
        source_file: str | None = None,
        files: dict[str, str] | None = None,
        timeout: float | None = None,
        env: dict[str, str] | None = None,
        chip_count: int | None = None,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
        limits: dict | None = None,
        emit=None,
    ) -> Result:
        """Run one request inside the executor_id's session sandbox.

        No retry wrapper: an infra failure means the session's
        sandbox (and its state) is gone — retrying on a replacement would
        silently pretend the state survived. The session is closed and the
        error surfaces; the client decides whether to rebuild.
        """
        if not OBJECT_ID_RE.match(executor_id):
            raise ValueError(
                "invalid executor_id (want ^[0-9a-zA-Z_-]{1,255}$)"
            )
        lane, files, timeout, limits_payload = self._validate_request(
            source_code, source_file, files, timeout, chip_count, limits
        )
        timer = PhaseTimer()
        # Sessions never retry, so one draft covers the whole request.
        # The commit lives in the OUTER finally, not the loop body's: the
        # closed-while-waiting `continue` passes through the inner finally,
        # and committing there would mark the (still empty) draft spent —
        # the retry iteration's real consumption would then never bill.
        usage = self._usage_draft(tenant)
        loop = asyncio.get_running_loop()
        try:
            return await self._session_loop(
                executor_id,
                lane,
                source_code,
                source_file,
                files,
                timeout,
                env,
                timer,
                limits_payload,
                chip_count=chip_count,
                tenant=tenant,
                priority=priority,
                deadline=deadline,
                emit=emit,
                usage=usage,
                loop=loop,
            )
        finally:
            # Attribution commits on EVERY exit — success, violation, or
            # fault: the draft holds whatever the session run measured.
            self.usage.commit(usage)

    async def _session_loop(
        self,
        executor_id: str,
        lane: int,
        source_code,
        source_file,
        files,
        timeout,
        env,
        timer: PhaseTimer,
        limits_payload,
        *,
        chip_count,
        tenant,
        priority,
        deadline,
        emit,
        usage,
        loop,
    ) -> Result:
        while True:
            with timer.phase("queue_wait"):
                session = await self._get_session(
                    executor_id,
                    lane,
                    tenant=tenant,
                    priority=priority,
                    deadline=deadline,
                )
                await session.lock.acquire()
            try:
                if session.closed or self._sessions.get(executor_id) is not session:
                    continue  # closed while we waited for the lock; recreate
                if chip_count is not None and session.lane != lane:
                    raise ValueError(
                        f"session {executor_id} runs on a {session.lane}-chip "
                        f"sandbox; requested chip_count={chip_count}"
                    )
                assert session.sandbox is not None
                session.last_used = loop.time()
                if session.pending_restore is not None:
                    # First turn after a hibernate/migrate: rehydrate the
                    # fresh sandbox from the durable checkpoint before the
                    # user code runs. A wire failure mid-restore raises
                    # ExecutorError below — the session closes and the
                    # RECORD SURVIVES (blob intact), so the retry restores
                    # again; a half-restored sandbox is never served.
                    try:
                        with timer.phase("restore"):
                            restored = await self._restore_session(
                                executor_id, session
                            )
                    except (ExecutorError, SandboxSpawnError):
                        self._end_session_soon(executor_id, session, recycle=False)
                        raise
                    except asyncio.CancelledError:
                        self._end_session_soon(executor_id, session, recycle=False)
                        raise
                    if not restored:
                        # Clean refusal (version skew / corrupt state): the
                        # record is already evicted — close this sandbox
                        # (its workspace may hold the partial upload) and
                        # recreate GENUINELY fresh: the turn still succeeds,
                        # with an honest session_seq=1 reporting state loss.
                        await self._end_session(executor_id, session, recycle=True)
                        continue
                try:
                    result, continuable = await self._run_on_sandbox(
                        session.sandbox,
                        source_code,
                        source_file,
                        files,
                        timeout,
                        env,
                        timer,
                        limits=limits_payload,
                        emit=emit,
                        usage=usage,
                    )
                except LimitExceededError as e:
                    # A violation breaks the session either way: the killed
                    # runner lost its state, and even an in-process guard
                    # leaves the workspace in whatever shape the runaway
                    # left it. Recycle the host only if its runner survived.
                    self._end_session_soon(
                        executor_id, session, recycle=e.continuable
                    )
                    raise
                except (ExecutorError, SandboxSpawnError):
                    # The sandbox is unreachable/broken: session state is
                    # already lost — close it so the id can start fresh.
                    self._end_session_soon(executor_id, session, recycle=False)
                    raise
                except asyncio.CancelledError:
                    # Client disconnect mid-request: the sandbox server is
                    # still running the orphaned script and mutating the
                    # workspace — the session contract is unrecoverable.
                    self._end_session_soon(executor_id, session, recycle=False)
                    raise
                session.last_used = loop.time()
                session.seq += 1
                result.session_seq = session.seq
                if not continuable:
                    # A host's warm runner was killed (timeout) or crashed:
                    # in-process state is gone, so the session contract is
                    # broken. Close it (reported via session_ended); turnover
                    # decides recycle-vs-dispose (the server refuses /reset
                    # mid-rewarm).
                    result.session_ended = True
                    self._end_session_soon(executor_id, session, recycle=True)
                return result
            finally:
                session.lock.release()

    async def _get_session(
        self,
        executor_id: str,
        lane: int,
        *,
        tenant: str | None = None,
        priority: str | None = None,
        deadline: float | None = None,
    ) -> _Session:
        """Fetch or create the id's session. Concurrent first requests wait
        on one creation (the `ready` future) instead of racing spawns.
        Admission params apply to the CREATING request's slot acquisition;
        follow-up requests ride the already-held sandbox."""
        while True:
            session = self._sessions.get(executor_id)
            if session is not None:
                if session.restoring:
                    # The session is mid-restore from its checkpoint: one
                    # turn owns the restore; a second admitted now would
                    # race a double-restore into the same sandbox. Typed,
                    # retryable, NOT session-ending — HTTP 409 +
                    # Retry-After / gRPC UNAVAILABLE + x-session-restoring.
                    raise SessionRestoringError(
                        f"session {executor_id} is restoring from its "
                        "durable checkpoint; retry shortly",
                        executor_id=executor_id,
                        retry_after=1.0,
                    )
                if session.sandbox is None and not session.closed:
                    await asyncio.shield(session.ready)
                if session.closed:
                    # Closed while we waited; loop and re-create against
                    # current table state.
                    continue
                return session
            active = sum(1 for s in self._sessions.values() if not s.closed)
            if active >= self.config.executor_session_max:
                raise SessionLimitError(
                    f"too many active sessions "
                    f"({active}/{self.config.executor_session_max}); retry "
                    "later or close one via DELETE /v1/executors/{id}"
                )
            # A hibernated checkpoint wakes here: the durable record
            # (replica-coherent — a peer may have written it) pins the
            # session's lane and starting seq, and the record itself rides
            # the new session as pending_restore, applied lazily under the
            # session lock on this first turn (phases.restore reports the
            # cost). A corrupt/expired record loads as None and the
            # session recreates fresh with an honest seq reset.
            record = await self.session_store.load(tenant, executor_id)
            if record is not None:
                lane = int(record.get("lane", lane))
            session = _Session(lane=lane, last_used=asyncio.get_running_loop().time())
            session.tenant = tenant
            if record is not None:
                session.pending_restore = record
                session.seq = int(record.get("seq", 0))
            self._sessions[executor_id] = session
            try:
                sandbox = await self._acquire(
                    lane, tenant=tenant, priority=priority, deadline=deadline
                )
            except BaseException as e:
                session.closed = True
                if self._sessions.get(executor_id) is session:
                    del self._sessions[executor_id]
                if isinstance(e, asyncio.CancelledError):
                    # The CREATOR was cancelled (its client disconnected).
                    # Waiters parked on `ready` are unrelated requests —
                    # cancelling them too would drop their connections with
                    # no response; give them a retryable infra error instead.
                    session.ready.set_exception(
                        ExecutorError(
                            f"session {executor_id} creation was cancelled"
                        )
                    )
                else:
                    session.ready.set_exception(e)
                # The future may have no waiters; don't warn about it.
                session.ready.exception()
                raise
            # Move the hold from in_use ("due back to the pool shortly") to
            # session_held ("parked until the session closes"): waiters and
            # the refill logic treat the two very differently.
            self._in_use[lane] = max(0, self._in_use.get(lane, 0) - 1)
            self._session_held[lane] = self._session_held.get(lane, 0) + 1
            self._notify_lane(lane)
            session.sandbox = sandbox
            session.ready.set_result(True)
            logger.info(
                "session %s opened (lane=%d, sandbox=%s)",
                executor_id,
                lane,
                sandbox.id,
            )
            return session

    def _detach_session(
        self, executor_id: str, session: _Session
    ) -> Sandbox | None:
        """Synchronously mark THIS session closed and drop its table entry
        (identity-checked: a caller that waited on a stale lock must not
        tear down a successor session that reused the id). Returns the
        sandbox still needing turnover, or None."""
        if session is None or session.closed:
            return None
        if self._sessions.get(executor_id) is session:
            del self._sessions[executor_id]
        session.closed = True
        return session.sandbox

    async def _drop_session_sandbox(
        self, lane: int, sandbox: Sandbox, *, recycle: bool
    ) -> None:
        """Turn over a detached session's sandbox. The slot stays counted in
        _session_held until the sandbox is actually pooled or disposed —
        freeing it first would let a constrained-lane waiter start a spawn
        that blocks on the physical chip this sandbox still owns (same
        invariant as _release, which decrements _in_use only after turnover).
        extra_free lets the recycle decision see the slot as available."""
        try:
            await self._turnover(sandbox, lane, recycle, extra_free=1)
        finally:
            self._session_held[lane] = max(0, self._session_held.get(lane, 0) - 1)
            self._notify_all_lanes()

    async def _end_session(
        self, executor_id: str, session: _Session, *, recycle: bool
    ) -> bool:
        """Close THIS session (caller holds its lock, or knows it is idle):
        release the lane slot and hand the sandbox to turnover."""
        sandbox = self._detach_session(executor_id, session)
        if sandbox is None:
            return False
        logger.info(
            "session %s closed (lane=%d, sandbox=%s)",
            executor_id,
            session.lane,
            sandbox.id,
        )
        await self._drop_session_sandbox(session.lane, sandbox, recycle=recycle)
        return True

    def _end_session_soon(
        self, executor_id: str, session: _Session, *, recycle: bool
    ) -> None:
        """Close THIS session with turnover off the hot path: detach
        SYNCHRONOUSLY (a new request must not grab the doomed session, and a
        cancelled caller must not lose the teardown to a second cancel),
        then reset/dispose in a tracked background task — the same
        discipline as the stateless release (close() awaits the task)."""
        sandbox = self._detach_session(executor_id, session)
        if sandbox is None:
            return
        logger.info(
            "session %s closed (lane=%d, sandbox=%s)",
            executor_id,
            session.lane,
            sandbox.id,
        )
        task = asyncio.get_running_loop().create_task(
            self._off_request_path(
                self._drop_session_sandbox(session.lane, sandbox, recycle=recycle)
            )
        )
        self._dispose_tasks.add(task)
        task.add_done_callback(self._dispose_tasks.discard)

    # ------------------------------------------------- session durability

    async def _restore_session(self, executor_id: str, session: _Session) -> bool:
        """Rehydrate a fresh sandbox from the session's durable checkpoint
        (caller holds the session lock). Workspace bytes ride the existing
        delta upload path — a fresh sandbox's manifest is empty so every
        file moves, but conditional PUTs and the content-addressed store
        keep the movement to what the sandbox does not already hold — then
        POST /restore ships the interpreter state to every host of the
        slice (host 0's state is the checkpoint; per JAX convention host 0
        owns the singular side effects, and module-level state must agree
        across the SPMD group).

        Returns True when the checkpoint applied (seq continues from the
        record) and False on a CLEAN refusal (bad_state_version /
        corrupt_state): the runner decodes every blob before mutating
        anything, so a refusal leaves it untouched — but the workspace
        upload may have landed, so the caller must still recreate the
        session on a fresh sandbox. The record is evicted here either way
        on refusal. A wire failure raises ExecutorError and KEEPS the
        record: the blob is intact, the next attempt restores again."""
        record = session.pending_restore
        assert record is not None and session.sandbox is not None
        sandbox = session.sandbox
        session.restoring = True
        try:
            self._check_lease(sandbox)
            client = self._http_client()
            hosts = sandbox.host_urls
            workspace = record.get("workspace") or {}
            files = {
                f"/workspace/{rel}": object_id
                for rel, object_id in workspace.items()
            }
            if files:
                await self._upload_inputs(
                    client,
                    hosts,
                    self._transfer_state(sandbox),
                    files,
                    TransferStats(),
                )
            payload = {
                "state": record.get("interp") or {},
                "timeout": self.config.session_snapshot_timeout,
            }
            replies = await asyncio.gather(
                *(
                    self._post_snapshot_op(client, base, "restore", payload, sandbox)
                    for base in hosts
                )
            )
            if all(reply.get("ok") for reply in replies):
                session.pending_restore = None
                session.seq = int(record.get("seq", session.seq))
                self.session_store.restores += 1
                self.metrics.session_restores.inc(outcome="restored")
                logger.info(
                    "session %s restored from checkpoint (seq=%d, files=%d)",
                    executor_id,
                    session.seq,
                    len(files),
                )
                return True
            reason = next(
                (
                    str(reply.get("reason") or "refused")
                    for reply in replies
                    if not reply.get("ok")
                ),
                "refused",
            )
            await self.session_store.delete(session.tenant, executor_id)
            session.pending_restore = None
            self.metrics.session_restores.inc(outcome="fresh")
            logger.warning(
                "session %s checkpoint refused by runner (%s): record "
                "evicted, recreating fresh",
                executor_id,
                reason,
            )
            return False
        finally:
            session.restoring = False

    async def _post_snapshot_op(
        self,
        client: httpx.AsyncClient,
        base: str,
        op: str,
        payload: dict,
        sandbox: Sandbox,
    ) -> dict:
        """One host's /snapshot or /restore round-trip: lease-headered like
        every dispatch, typed-409-aware, and strict about the reply shape —
        any wire or protocol failure is an ExecutorError (the caller's
        session close / record-keep semantics key off that type)."""
        timeout = float(payload.get("timeout", 30.0)) + 10.0
        try:
            resp = await client.post(
                f"{base}/{op}",
                json=payload,
                timeout=timeout,
                headers=self._wire_headers(sandbox),
            )
        except httpx.HTTPError as e:
            raise ExecutorError(f"session {op} to {base} failed: {e}")
        self._raise_if_stale_lease(resp, sandbox)
        if resp.status_code != 200:
            raise ExecutorError(
                f"session {op} to {base} failed: {resp.status_code} "
                f"{resp.text[:200]}"
            )
        try:
            body = resp.json()
        except ValueError:
            raise ExecutorError(f"session {op} to {base} returned a bad body")
        if not isinstance(body, dict):
            raise ExecutorError(f"session {op} to {base} returned a bad body")
        return body

    async def _snapshot_interp(self, sandbox: Sandbox) -> dict:
        """Capture host 0's interpreter state (env deltas, cwd, workspace
        modules' plain-data globals, installed packages) via the runner's
        snapshot op. Raises ExecutorError when the runner refuses (e.g.
        state_too_large) — the hibernate caller degrades gracefully by
        leaving the session parked."""
        client = self._http_client()
        body = await self._post_snapshot_op(
            client,
            sandbox.host_urls[0],
            "snapshot",
            {
                "timeout": self.config.session_snapshot_timeout,
                "max_bytes": self.config.session_snapshot_max_bytes,
            },
            sandbox,
        )
        if not body.get("ok") or not isinstance(body.get("state"), dict):
            raise ExecutorError(
                "session snapshot refused: "
                f"{body.get('reason', 'no state returned')}"
            )
        return body["state"]

    async def _capture_workspace(self, sandbox: Sandbox) -> dict[str, str]:
        """Fold host 0's workspace into content-addressed storage and return
        {rel: object id}. Manifest-sha-negotiated: a file whose sha already
        exists() in storage records the mapping and moves ZERO bytes — the
        common hibernate (unchanged workspace since the last download
        phase) is pure bookkeeping. A legacy executor (no manifest route)
        fails the hibernate instead of checkpointing blind."""
        client = self._http_client()
        base = sandbox.host_urls[0]
        try:
            resp = await client.get(f"{base}/workspace-manifest")
        except httpx.HTTPError as e:
            raise ExecutorError(f"workspace manifest fetch failed: {e}")
        if resp.status_code != 200:
            raise ExecutorError(
                f"workspace manifest fetch failed: {resp.status_code} "
                "(legacy executor binaries cannot hibernate)"
            )
        try:
            entries = resp.json().get("files", {})
        except ValueError:
            raise ExecutorError("workspace manifest fetch returned a bad body")
        if not isinstance(entries, dict):
            raise ExecutorError("workspace manifest fetch returned a bad body")

        async def capture(rel: str, sha) -> tuple[str, str]:
            if isinstance(sha, str) and SHA256_HEX_RE.match(sha):
                if await self.storage.exists(sha):
                    return rel, sha
            _, object_id, _ = await self._download_file(client, base, rel)
            return rel, object_id

        captured = await asyncio.gather(
            *(capture(rel, sha) for rel, sha in sorted(entries.items()))
        )
        return dict(captured)

    async def _hibernate_session(
        self, executor_id: str, session: _Session, *, reason: str = "hibernate"
    ) -> bool:
        """Checkpoint THIS session into the durable store and release its
        chip (caller holds the session lock). Returns True when the session
        ended with its state durable — the sweep's hibernate leg and the
        fence path's migrate leg both ride this. A session that never woke
        from its previous checkpoint (pending_restore still set) just ends:
        the admitted record IS its state, byte-for-byte."""
        sandbox = session.sandbox
        if sandbox is None or session.closed:
            return False
        if session.pending_restore is not None:
            # Parked-but-never-woken: nothing ran since the checkpoint was
            # admitted, so the record already holds the exact state.
            await self._end_session(executor_id, session, recycle=True)
            self.metrics.session_hibernates.inc(outcome=reason)
            return True
        try:
            interp_state = await self._snapshot_interp(sandbox)
            workspace = await self._capture_workspace(sandbox)
        except (ExecutorError, SandboxSpawnError) as e:
            self.metrics.session_hibernates.inc(outcome="failed")
            logger.warning(
                "session %s %s checkpoint failed (%s); leaving it parked",
                executor_id,
                reason,
                e,
            )
            return False
        outcome = await self.session_store.save(
            session.tenant,
            executor_id,
            lane=session.lane,
            seq=session.seq,
            interp_state=interp_state,
            workspace=workspace,
            reason=reason,
        )
        if outcome != "admitted":
            self.metrics.session_hibernates.inc(outcome="failed")
            logger.warning(
                "session %s %s checkpoint not admitted (%s); leaving it "
                "parked",
                executor_id,
                reason,
                outcome,
            )
            return False
        await self._end_session(executor_id, session, recycle=True)
        self.metrics.session_hibernates.inc(outcome=reason)
        logger.info(
            "session %s hibernated (%s): seq=%d, %d workspace files, chip "
            "released to lane %d",
            executor_id,
            reason,
            session.seq,
            len(workspace),
            session.lane,
        )
        return True

    async def _migrate_session(
        self, executor_id: str, session: _Session, reason: str
    ) -> bool:
        """Live-migrate one session off a host being fenced: bounded lock
        wait (an in-flight request finishes its turn first), then the
        hibernate path with reason="migrate" — the durable record restores
        the session behind ANY replica on its next turn, session_seq
        continuous, zero client-visible state loss. Returns False when the
        snapshot cannot be taken in time; the caller falls back to the
        pre-durability force-close."""
        try:
            await asyncio.wait_for(
                session.lock.acquire(),
                timeout=self.config.session_snapshot_timeout,
            )
        except asyncio.TimeoutError:
            return False
        try:
            if session.closed or self._sessions.get(executor_id) is not session:
                return True  # already gone — nothing to lose
            ok = await self._hibernate_session(
                executor_id, session, reason="migrate"
            )
            self.metrics.session_migrations.inc(
                outcome="saved" if ok else "forced"
            )
            return ok
        finally:
            session.lock.release()

    def _account_idle(self, session: _Session, now: float) -> None:
        """Fold this session's parked-idle time since the last sweep into
        the idle-chip-seconds counter (satellite: make the cost hibernation
        kills VISIBLE). Busy sessions reset the watermark — time under the
        lock is work, not waste."""
        if session.lock.locked() or session.sandbox is None:
            session.idle_accounted = now
            return
        since = max(session.last_used, session.idle_accounted)
        delta = max(0.0, now - since)
        if delta <= 0.0:
            return
        chips = max(1, session.lane or 1)
        self._idle_chip_seconds += delta * chips
        self.metrics.session_idle_chip_seconds.inc(delta * chips)
        session.idle_accounted = now

    def list_sessions(self) -> list[dict]:
        """Live sessions for GET /v1/executors: id, lane, idle seconds,
        whether a request is in flight, and requests served. Sessions still
        spawning their sandbox are included (status "spawning") — they count
        toward executor_session_max, so hiding them would make the list
        contradict the cap's own error message."""
        now = asyncio.get_running_loop().time()
        return [
            {
                "executor_id": executor_id,
                "chip_count": session.lane,
                "idle_s": round(max(0.0, now - session.last_used), 3),
                "busy": session.lock.locked(),
                "requests": session.seq,
                "status": "ready" if session.sandbox is not None else "spawning",
            }
            for executor_id, session in self._sessions.items()
            if not session.closed
        ]

    async def close_session(
        self, executor_id: str, *, tenant: str | None = None
    ) -> bool:
        """Explicitly end a session (DELETE /v1/executors/{id}). Waits for an
        in-flight request on the session to finish first. Returns False if no
        such session exists. The durable checkpoint (if any) is evicted too:
        an explicit close means the client is done — the record must not
        resurrect the session on an id reuse."""
        session = self._sessions.get(executor_id)
        if session is None or session.closed:
            # No live session — but a HIBERNATED one may exist as a record
            # only. Deleting it IS the close; report it as one.
            return await self.session_store.delete(tenant, executor_id)
        await self.session_store.delete(session.tenant or tenant, executor_id)
        if session.sandbox is None:
            try:
                await asyncio.shield(session.ready)
            except asyncio.CancelledError:
                raise  # the CALLER was cancelled — do not swallow it
            except Exception:  # noqa: BLE001 — creation failed = closed
                return False
        async with session.lock:
            # `closed` may have flipped while we waited for the lock (e.g.
            # the in-flight request hit runner_restarted and ended the
            # session itself); _end_session's identity check then keeps a
            # successor session under the same id untouched.
            return await self._end_session(executor_id, session, recycle=True)

    async def sweep_sessions(self) -> int:
        """Close sessions idle past the configured timeout. An idle session
        parks a sandbox (on TPU lanes: physical chips) indefinitely; the
        sweep bounds that at executor_session_idle_timeout.

        With the durability plane live, a cheaper bound fires FIRST: a
        session idle past session_hibernate_idle_seconds is checkpointed
        and its chip released (the autoscaler sees the reclaimed supply),
        instead of waiting for the hard expiry. A failed hibernate leaves
        the session parked — the plain idle close still bounds it. The
        sweep also folds parked-idle time into the idle-chip-seconds
        counter, and TTL-prunes durable records nobody woke."""
        loop = asyncio.get_running_loop()
        idle_cutoff = self.config.executor_session_idle_timeout
        hibernate_after = (
            self.config.session_hibernate_idle_seconds
            if self.session_store.enabled
            else 0.0
        )
        closed = 0
        for executor_id, session in list(self._sessions.items()):
            if session.closed or session.sandbox is None:
                continue
            self._account_idle(session, loop.time())
            if session.lock.locked():  # request in flight
                continue
            idle = loop.time() - session.last_used
            if hibernate_after > 0 and idle >= hibernate_after:
                async with session.lock:
                    # Re-check under the lock: a request may have slipped in.
                    if (
                        self._sessions.get(executor_id) is session
                        and not session.closed
                        and loop.time() - session.last_used >= hibernate_after
                    ):
                        if await self._hibernate_session(executor_id, session):
                            closed += 1
                            continue
                if self._sessions.get(executor_id) is not session or session.closed:
                    continue
                idle = loop.time() - session.last_used
            if idle < idle_cutoff:
                continue
            async with session.lock:
                # Re-check under the lock: a request may have slipped in.
                if (
                    self._sessions.get(executor_id) is session
                    and loop.time() - session.last_used >= idle_cutoff
                ):
                    if await self._end_session(executor_id, session, recycle=True):
                        logger.info("session %s expired (idle)", executor_id)
                        closed += 1
        try:
            self.session_store.sweep_expired()
        except Exception:  # noqa: BLE001 — pruning must not break the sweep
            logger.warning("session record TTL sweep failed", exc_info=True)
        return closed

    def start_session_sweeper(self, interval: float | None = None) -> asyncio.Task | None:
        """Run sweep_sessions periodically until close(). Default cadence:
        a quarter of the idle timeout, so expiry lands within ~125% of it —
        tightened to half the hibernate threshold when the durability plane
        is live, so a hibernation lands within ~150% of its own bound too."""
        if self.config.executor_session_max <= 0:
            return None
        if interval is None:
            interval = max(1.0, self.config.executor_session_idle_timeout / 4)
            if (
                self.session_store.enabled
                and self.config.session_hibernate_idle_seconds > 0
            ):
                interval = min(
                    interval,
                    max(1.0, self.config.session_hibernate_idle_seconds / 2),
                )
        return self._start_sweeper(self.sweep_sessions, interval, "session sweep")

    def _start_sweeper(self, sweep, interval: float, label: str) -> asyncio.Task | None:
        """Shared periodic-sweep loop: run `sweep` every `interval` seconds
        until close(), logging (not dying on) failures."""
        if interval <= 0:
            return None

        async def sweeper() -> None:
            while not self._closed:
                await asyncio.sleep(interval)
                try:
                    await sweep()
                except Exception:  # noqa: BLE001 — keep sweeping
                    logger.exception("%s failed", label)

        task = asyncio.get_running_loop().create_task(sweeper())
        self._fill_tasks.add(task)  # cancelled/awaited by close()
        task.add_done_callback(self._fill_tasks.discard)
        return task

    async def _call_host(
        self,
        client: httpx.AsyncClient,
        index: int,
        base: str,
        payload: dict,
        timeout: float,
        sandbox: Sandbox,
        emit,
    ) -> dict:
        """One host's /execute round-trip inside its own trace span. The
        `traceparent` for the wire hop is read back out of the contextvar by
        `_trace_headers` (keeping `_post_execute`'s signature stable — tests
        monkeypatch it), and the sandbox's in-process phase timings come
        back in the response's `trace` block and graft in as child spans."""
        with self.tracer.span(
            "executor.execute", attributes={"host": base, "host_index": index}
        ) as span:
            if emit is not None and index == 0:
                body = await self._post_execute_stream(
                    client, base, payload, timeout, sandbox, emit
                )
            else:
                body = await self._post_execute(
                    client, base, payload, timeout, sandbox
                )
            self._graft_sandbox_trace(span, base, body)
            return body

    def _trace_headers(self) -> dict | None:
        """Headers propagating the current span's context to a sandbox (the
        executor server echoes the value and stamps its phase timings into a
        `trace` block). None when there is nothing to propagate."""
        span = tracing.current_span()
        if span is None:
            return None
        traceparent = span.traceparent()
        if traceparent is None:
            return None
        return {"traceparent": traceparent}

    def _graft_sandbox_trace(self, span, base: str, body) -> None:
        """Fold a sandbox's reported per-phase timings (install/exec/collect,
        measured in-process by executor/server.cpp) into the trace as
        children of this host's executor.execute span. Offsets are relative
        to the sandbox's own request start and are applied to THIS span's
        start time, so cross-process clock skew never enters the math (the
        child spans are guaranteed to nest inside the HTTP call window)."""
        if not span.recording or not isinstance(body, dict):
            return
        block = body.get("trace")
        entries = block.get("spans") if isinstance(block, dict) else None
        if not isinstance(entries, list):
            return
        for entry in entries:
            if not isinstance(entry, dict):
                continue
            name = entry.get("name")
            offset = entry.get("start_offset_s")
            duration = entry.get("duration_s")
            if (
                not isinstance(name, str)
                or not name
                or not isinstance(offset, (int, float))
                or not isinstance(duration, (int, float))
            ):
                continue
            self.tracer.record_span(
                f"sandbox.{name}"[:64],
                trace_id=span.trace_id,
                parent_id=span.span_id,
                start_unix=span.start_unix + max(0.0, float(offset)),
                duration_s=float(duration),
                attributes={"host": base},
            )

    async def _post_execute_stream(
        self,
        client: httpx.AsyncClient,
        base: str,
        payload: dict,
        timeout: float,
        sandbox: Sandbox,
        emit,
    ) -> dict:
        """POST /execute/stream: NDJSON events — {"stream","data"} chunks
        passed to `emit` as they arrive, then a final object that is the
        complete /execute response body (returned)."""
        final: dict | None = None
        try:
            async with client.stream(
                "POST",
                f"{base}/execute/stream",
                json=payload,
                headers=self._wire_headers(sandbox),
                timeout=httpx.Timeout(timeout + 30.0, read=timeout + 30.0),
            ) as resp:
                if resp.status_code == 403:
                    # Client path error (e.g. source_file escapes the
                    # workspace) — same mapping as _post_execute, so the
                    # streamed surface returns 400, not a 502 infra error.
                    text = (await resp.aread()).decode(errors="replace")
                    try:
                        message = json.loads(text).get("error", "forbidden path")
                    except ValueError:
                        message = "forbidden path"
                    raise ValueError(message)
                if resp.status_code != 200:
                    text = (await resp.aread()).decode(errors="replace")
                    if resp.status_code == 409:
                        # The typed stale-lease refusal, stream flavor.
                        try:
                            body = json.loads(text)
                        except ValueError:
                            body = None
                        if (
                            isinstance(body, dict)
                            and body.get("error") == "stale_lease"
                        ):
                            raise StaleLeaseError(
                                f"sandbox {sandbox.id} rejected a stale "
                                f"lease claim (held {body.get('held')!r}, "
                                f"offered {body.get('offered')!r})"
                            )
                    # Refusal before any run — exempt from fault billing
                    # like _post_execute's non-200 path.
                    error = ExecutorError(
                        f"sandbox {sandbox.id} ({base}) /execute/stream -> "
                        f"{resp.status_code}: {text[:500]}"
                    )
                    error.device_may_have_run = False
                    raise error
                buffer = ""
                async for text in resp.aiter_text():
                    buffer += text
                    while "\n" in buffer:
                        line, buffer = buffer.split("\n", 1)
                        if not line.strip():
                            continue
                        try:
                            event = json.loads(line)
                        except ValueError as e:
                            raise ExecutorError(
                                f"sandbox {sandbox.id} ({base}) sent a "
                                f"malformed stream event: {e}"
                            )
                        if "stream" in event:
                            await emit(
                                {
                                    "stream": event.get("stream", ""),
                                    "data": event.get("data", ""),
                                }
                            )
                        else:
                            final = event
        except httpx.HTTPError as e:
            raise ExecutorError(f"sandbox {sandbox.id} ({base}) unreachable: {e}")
        if final is None:
            raise ExecutorError(
                f"sandbox {sandbox.id} ({base}) stream ended without a result"
            )
        if "error" in final and "exit_code" not in final:
            raise ExecutorError(
                f"sandbox {sandbox.id} ({base}): {final['error']}"
            )
        return final

    async def _post_execute(
        self,
        client: httpx.AsyncClient,
        base: str,
        payload: dict,
        timeout: float,
        sandbox: Sandbox,
    ) -> dict:
        try:
            resp = await client.post(
                f"{base}/execute",
                json=payload,
                headers=self._wire_headers(sandbox),
                timeout=httpx.Timeout(timeout + 30.0),
            )
        except httpx.HTTPError as e:
            raise ExecutorError(f"sandbox {sandbox.id} ({base}) unreachable: {e}")
        if resp.status_code == 403:
            raise ValueError(resp.json().get("error", "forbidden path"))
        # The executor's typed stale-lease refusal: this claim's generation
        # was fenced and a successor holds the chips — never retried
        # against this host (the retry ladder acquires a fresh sandbox).
        self._raise_if_stale_lease(resp, sandbox)
        if resp.status_code != 200:
            # A non-200 from /execute is a refusal BEFORE any run (the
            # executor returns 200 even for violations and timeouts):
            # usage billing must not charge device time for it.
            error = ExecutorError(
                f"sandbox {sandbox.id} ({base}) /execute -> {resp.status_code}: "
                f"{resp.text[:500]}"
            )
            error.device_may_have_run = False
            raise error
        try:
            return resp.json()
        except ValueError as e:
            raise ExecutorError(
                f"sandbox {sandbox.id} ({base}) returned malformed JSON: {e}"
            )

    def _cache_sync(self, sandbox: Sandbox) -> SandboxCacheSync:
        """The sandbox's compile-cache sync state, riding in `meta` like the
        transfer manifests (generation turnover preserves the cache dir, so
        unlike those this state is never reset)."""
        sync = sandbox.meta.get("compile_cache")
        if not isinstance(sync, SandboxCacheSync):
            # harvest_allowed is re-evaluated INSIDE the sync at every
            # admission: on a shared cache dir the revoking tenant run is
            # on a different sandbox, so the revocation can land while
            # this sandbox's harvest is mid-flight awaiting the network.
            sync = SandboxCacheSync(
                self.compile_cache,
                harvest_allowed=self._harvest_still_trusted,
            )
            sandbox.meta["compile_cache"] = sync
        return sync

    async def _off_request_path(self, coro):
        """Run background pool work (refills, releases, session drops) with
        the trace context CLEARED: asyncio tasks snapshot their creator's
        contextvars, so a refill or post-response release created inside a
        request would otherwise keep attaching late spans/events to that
        request's (long-closed) trace — making its span set
        nondeterministic. Inside these tasks, child-span factories see no
        current span and no-op; work awaited ON a request path still
        traces normally."""
        tracing.current_span_var.set(None)
        return await coro

    async def _seed_compile_cache(
        self, sandbox: Sandbox, *, traced: bool = True
    ) -> None:
        """Push the fleet hot set into a fresh sandbox's cache dir (spawn
        path). Entries the host already holds move no bytes; a legacy
        executor (404 on the manifest route) is remembered and never probed
        again. Failures cost a recompile, never a spawn. The span is a
        child of the requesting trace for direct (in-request) spawns;
        background refills pass traced=False (a span finishing after its
        request's trace was read would make the span set nondeterministic)."""
        if not self.compile_cache.enabled:
            return
        sync = self._cache_sync(sandbox)
        try:
            with (
                self.tracer.span(
                    "compile_cache.seed", attributes={"sandbox": sandbox.id}
                )
                if traced
                else tracing.NOOP
            ) as span:
                stats = await sync.seed(self._http_client(), sandbox.host_urls)
                span.set_attribute("bytes_pushed", stats.pushed_bytes)
                span.set_attribute("files_pushed", stats.pushed_files)
                span.set_attribute("files_skipped", stats.skipped_files)
        except Exception:  # noqa: BLE001 — seeding is strictly best-effort
            logger.warning(
                "compile-cache seed failed for %s", sandbox.id, exc_info=True
            )
            return
        self.metrics.compile_cache_bytes.inc(
            stats.pushed_bytes, direction="seed"
        )
        self.metrics.compile_cache_files.inc(
            stats.pushed_files, direction="seed"
        )
        self.metrics.compile_cache_skipped_files.inc(
            stats.skipped_files, direction="seed"
        )
        # The first request served by this sandbox reports what seeding it
        # cost (Result.phases compile_cache_seeded_bytes).
        sync.pending_seed_bytes = stats.pushed_bytes
        if stats.pushed_files:
            logger.info(
                "seeded %d compile-cache entries (%d bytes) into %s",
                stats.pushed_files,
                stats.pushed_bytes,
                sandbox.id,
            )

    def _compile_cache_dir_scope(self) -> str:
        """The backend's trust statement about who can write a sandbox's
        cache dir (see SandboxBackend.compile_cache_dir_scope). Fail
        closed: a backend that declares nothing (or something unknown) is
        treated as "external" and never harvested."""
        scope = getattr(self.backend, "compile_cache_dir_scope", None)
        return scope if scope in ("private", "shared") else "external"

    def _harvest_still_trusted(self) -> bool:
        """Control-plane-level harvest trust AS OF NOW — the cache-dir
        scopes a sandbox's own taint can't speak for. Handed to every
        SandboxCacheSync so it is re-evaluated mid-harvest at each
        admission (the revoking event — a tenant run on a DIFFERENT
        sandbox sharing the dir — can land while a harvest is awaiting
        the network)."""
        scope = self._compile_cache_dir_scope()
        if scope == "external":
            return False
        return not (scope == "shared" and self._shared_cache_tainted)

    async def _harvest_compile_cache(self, sandbox: Sandbox) -> None:
        """Pull never-seen compiled kernels out of a sandbox's cache dir
        (turnover/teardown path, off the request hot path). The manifest's
        shas are negotiated against the store first, so a sandbox that only
        used seeded kernels moves zero bytes.

        Provenance-gated on the backend's cache-dir scope: with a PRIVATE
        dir, only sandboxes that have NEVER run tenant code (untainted —
        in practice the pre-warm runs) are harvested; with a SHARED dir
        (local backend default — the fleet-constant path jax's key
        hashing demands) any tenant run anywhere taints the whole dir,
        so harvest stops control-plane-wide at the first tenant execute
        (the backend starts the dir empty, so the trusted-only epoch is
        airtight); an EXTERNAL dir (k8s PVC/hostPath) is writable by
        parties this control plane never sees and is never harvested. A
        tainted dir is attacker-writable and its artifacts are serialized
        executables every seeded sandbox would run, so it gets no harvest
        HTTP at all — not even the manifest probe."""
        if not self.compile_cache.enabled:
            return
        if not self._harvest_still_trusted():
            return
        sync = self._cache_sync(sandbox)
        if sync.tainted:
            return
        try:
            with self.tracer.span(
                "compile_cache.harvest", attributes={"sandbox": sandbox.id}
            ) as span:
                stats = await sync.harvest(
                    self._http_client(), sandbox.host_urls
                )
                span.set_attribute("bytes_harvested", stats.new_bytes)
                span.set_attribute("files_harvested", stats.new_files)
                span.set_attribute("files_known", stats.known_files)
                span.set_attribute("conflicts", stats.conflicts)
        except Exception:  # noqa: BLE001 — harvest is strictly best-effort
            logger.warning(
                "compile-cache harvest failed for %s", sandbox.id,
                exc_info=True,
            )
            return
        self.metrics.compile_cache_bytes.inc(
            stats.new_bytes, direction="harvest"
        )
        self.metrics.compile_cache_files.inc(
            stats.new_files, direction="harvest"
        )
        self.metrics.compile_cache_skipped_files.inc(
            stats.known_files, direction="harvest"
        )
        self.metrics.compile_cache_conflicts.inc(stats.conflicts)
        if stats.new_files:
            logger.info(
                "harvested %d new compile-cache entries (%d bytes) from %s",
                stats.new_files,
                stats.new_bytes,
                sandbox.id,
            )

    def _transfer_state(self, sandbox: Sandbox) -> SandboxTransfer:
        """The sandbox's per-host manifest cache, riding in `meta` so it
        follows the sandbox through pool recycles and session parking."""
        state = sandbox.meta.get("transfer")
        if not isinstance(state, SandboxTransfer):
            state = SandboxTransfer(
                enabled=self.config.transfer_manifest_enabled
            )
            sandbox.meta["transfer"] = state
        return state

    async def _upload_inputs(
        self,
        client: httpx.AsyncClient,
        hosts: list[str],
        transfer: SandboxTransfer,
        files: dict[str, str],
        stats: TransferStats,
    ) -> None:
        """The upload phase, delta-based: validate each DISTINCT object id
        exactly once (concurrently — `files` can map many paths to one id),
        then per host skip every path whose (rel, sha) already matches the
        manifest and stream only the rest. A session turn whose input files
        are unchanged uploads nothing at all."""
        rels: dict[str, str] = {}
        for path, object_id in files.items():
            rel = normalize_workspace_path(path)
            if rel.startswith("workspace/"):
                rel = rel[len("workspace/") :]
            rels[rel] = object_id
        unique_ids = sorted(set(rels.values()))

        async def sized(object_id: str) -> int:
            # size() doubles as the existence check — one stat per distinct
            # id covers both validation and byte accounting.
            try:
                return await self.storage.size(object_id)
            except StorageObjectNotFound:
                raise ValueError(f"unknown file object id: {object_id}") from None

        sizes = dict(
            zip(
                unique_ids,
                await asyncio.gather(*(sized(i) for i in unique_ids)),
            )
        )
        manifests = [transfer.host(base) for base in hosts]
        # State in doubt (runner killed mid-request earlier, or a failed
        # earlier resync): one manifest fetch per host — concurrently, like
        # the uploads — beats full re-uploads. Failure just leaves the
        # full-upload fallback.
        await asyncio.gather(
            *(
                self._resync_manifest(client, base, manifest)
                for base, manifest in zip(hosts, manifests)
                if manifest.entries is None and manifest.supports is not False
            )
        )
        uploads: list[tuple[str, str, str, HostManifest]] = []
        for base, manifest in zip(hosts, manifests):
            to_upload, skipped = manifest.delta(rels)
            stats.upload_skipped_files += len(skipped)
            stats.upload_skipped_bytes += sum(
                sizes[object_id] for object_id in skipped.values()
            )
            uploads.extend(
                (base, rel, object_id, manifest)
                for rel, object_id in to_upload.items()
            )
        # Input files never fully buffer in control-plane memory (a multi-GB
        # session file times N hosts would otherwise blow the heap).
        await asyncio.gather(
            *(
                self._upload_file(client, base, rel, object_id, manifest)
                for base, rel, object_id, manifest in uploads
            )
        )
        stats.upload_files += len(uploads)
        stats.upload_bytes += sum(
            sizes[object_id] for _, _, object_id, _ in uploads
        )

    async def _resync_manifest(
        self, client: httpx.AsyncClient, base: str, manifest: HostManifest
    ) -> None:
        """Recover a host's manifest from GET /workspace-manifest. A 404
        proves an old binary (remembered; never probed again); any other
        failure leaves the manifest unknown — full uploads now, retry on the
        next request."""
        try:
            resp = await client.get(f"{base}/workspace-manifest")
        except httpx.HTTPError:
            return
        if resp.status_code == 404:
            manifest.mark_legacy()
            return
        if resp.status_code != 200:
            return
        try:
            entries = resp.json().get("files", {})
        except ValueError:
            return
        if isinstance(entries, dict):
            manifest.resynced(
                {
                    rel: sha
                    for rel, sha in entries.items()
                    if isinstance(sha, str) and SHA256_HEX_RE.match(sha)
                }
            )

    async def _download_changed(
        self,
        client: httpx.AsyncClient,
        hosts: list[str],
        transfer: SandboxTransfer,
        bodies: list[dict],
        stats: TransferStats,
    ) -> dict[str, str]:
        """The download phase, hash-negotiated: each host's reported files
        fold into its manifest cache, then every changed path is fetched
        exactly once — host 0 wins path conflicts (it is the coordinator
        and, per JAX convention, the process that does singular side
        effects), and a path whose sha already exists() in storage records
        the mapping without moving bytes. A host answering without hashes
        (old binary) is marked legacy and downloads fully, exactly as the
        pre-manifest control plane did."""
        winner: dict[str, tuple[str, str | None]] = {}
        for base, body in zip(hosts, bodies):
            entries, has_hashes = parse_files_field(body.get("files", []))
            manifest = transfer.host(base)
            if not has_hashes:
                manifest.mark_legacy()
            else:
                deleted = body.get("deleted") or []
                manifest.apply_execute_response(
                    entries, deleted if isinstance(deleted, list) else []
                )
            for rel, sha in entries:
                winner.setdefault(rel, (base, sha))
        changed = await asyncio.gather(
            *(
                # The kill switch disables BOTH halves of the negotiation:
                # with transfer off, reported shas are ignored and every
                # changed file downloads fully, like the upload side.
                self._fetch_changed(
                    client, base, rel, sha if transfer.enabled else None, stats
                )
                for rel, (base, sha) in winner.items()
            )
        )
        return {f"/workspace/{rel}": object_id for rel, object_id in changed}

    async def _fetch_changed(
        self,
        client: httpx.AsyncClient,
        base: str,
        rel: str,
        sha: str | None,
        stats: TransferStats,
    ) -> tuple[str, str]:
        if sha is not None:
            try:
                size = await self.storage.size(sha)
            except (StorageObjectNotFound, ValueError):
                size = None
            if size is not None:
                # Hash negotiation: storage already holds these exact bytes
                # (the object id IS the sha) — record the mapping, move none.
                stats.download_skipped_files += 1
                stats.download_skipped_bytes += size
                return rel, sha
        rel, object_id, size = await self._download_file(client, base, rel)
        stats.download_files += 1
        stats.download_bytes += size
        return rel, object_id

    async def _upload_file(
        self,
        client: httpx.AsyncClient,
        base: str,
        rel: str,
        object_id: str,
        manifest: HostManifest,
    ) -> None:
        # `If-None-Match: <sha of the body being sent>` lets the server skip
        # the disk write (304) when the file already holds these bytes —
        # e.g. a path re-uploaded after the control plane lost its cache.
        # Old binaries ignore the header; legacy opaque ids can't claim one.
        headers = {}
        if manifest.supports is not False and SHA256_HEX_RE.match(object_id):
            headers["If-None-Match"] = object_id

        async def stream():
            async with self.storage.reader(object_id) as reader:
                while True:
                    data = await reader.read(1 << 20)
                    if not data:
                        return
                    yield data

        try:
            resp = await client.put(
                f"{base}/workspace/{rel}", content=stream(), headers=headers
            )
        except httpx.HTTPError as e:
            raise ExecutorError(f"upload of {rel} failed: {e}")
        if resp.status_code == 304:
            # Conditional hit: the host proved it already has this content.
            manifest.record_upload(rel, object_id)
            return
        if resp.status_code == 413:
            # The executor's workspace disk quota refused the upload: a
            # typed, deterministic violation (the host itself is fine —
            # the PUT was rejected before any damage).
            raise LimitExceededError(
                f"upload of {rel} exceeds the workspace disk quota",
                kind="disk_quota",
                continuable=True,
            )
        if resp.status_code != 200:
            raise ExecutorError(
                f"upload of {rel} failed: {resp.status_code} {resp.text[:200]}"
            )
        try:
            sha = resp.json().get("sha256")
        except ValueError:
            sha = None
        manifest.record_upload(rel, sha)

    async def _download_file(
        self, client: httpx.AsyncClient, base: str, rel: str
    ) -> tuple[str, str, int]:
        # Chunk-wise all the way: the executor serves the body via
        # sendfile(2) (never buffering the file in ITS memory) and the
        # control plane hashes it into Storage in bounded 1 MiB reads —
        # a multi-GB artifact never materializes whole on either side.
        try:
            async with self.storage.writer() as writer:
                async with client.stream("GET", f"{base}/workspace/{rel}") as resp:
                    if resp.status_code != 200:
                        raise ExecutorError(
                            f"download of {rel} failed: {resp.status_code}"
                        )
                    async for chunk in resp.aiter_bytes(1 << 20):
                        await writer.write(chunk)
        except httpx.HTTPError as e:
            raise ExecutorError(f"download of {rel} failed: {e}")
        assert writer.hash is not None
        return rel, writer.hash, writer.size

    def _release_soon(self, sandbox: Sandbox, lane: int, recyclable: bool) -> None:
        """Schedule the post-request release off the hot path (tracked so
        close() awaits it). `_releasing` is bumped SYNCHRONOUSLY — before
        the task first runs — so a next request arriving in the same event-
        loop window already sees this hold as supply-in-transit, not load."""
        self._releasing[lane] = self._releasing.get(lane, 0) + 1
        task = asyncio.get_running_loop().create_task(
            self._off_request_path(self._release(sandbox, lane, recyclable))
        )
        self._dispose_tasks.add(task)
        task.add_done_callback(self._dispose_tasks.discard)

    async def _release(self, sandbox: Sandbox, lane: int, recyclable: bool) -> None:
        """Post-request sandbox release for pool-acquired sandboxes: turnover
        plus the in-use bookkeeping waiters key off."""
        try:
            await self._turnover(sandbox, lane, recyclable)
        finally:
            self._releasing[lane] = max(0, self._releasing.get(lane, 0) - 1)
            self._in_use[lane] = max(0, self._in_use.get(lane, 0) - 1)
            self._notify_lane(lane)

    async def _turnover(
        self, sandbox: Sandbox, lane: int, recyclable: bool, *, extra_free: int = 0
    ) -> None:
        """Sandbox turnover (runs off the hot path): recycle the warm device
        process back into the pool when safe — the TPU lease survives and
        the next request pops a hot sandbox in milliseconds — else dispose
        it and refill the lane (VERDICT r2 #1)."""
        recycled: Sandbox | None = None
        # Harvest BEFORE reset/dispose: kernels this generation compiled
        # must reach the fleet store even when the sandbox itself is about
        # to die. A broken/unreachable sandbox just yields an empty harvest.
        await self._harvest_compile_cache(sandbox)
        try:
            if (
                recyclable
                and not self._closed
                and self.config.executor_reuse_sandboxes
                # A fenced host never recycles: its lease is revoked and
                # its process is being (or has been) disposed — pooling it
                # would hand requests a host whose every dispatch dies on
                # the stale-lease check.
                and not sandbox.meta.get("lease_fenced")
                # Recycle only while the pool is short of SUPPLY: under a
                # concurrency burst on an unconstrained lane, many
                # in-flight sandboxes release at once and the surplus must
                # be disposed, or live processes would grow past the lane
                # target and stay there. Wedged pooled hosts don't count —
                # a healthy recycle must not be disposed because zombies
                # occupy the deque.
                and self._pool_supply(lane) < self._lane_target(lane, extra_free=extra_free)
            ):
                try:
                    recycled = await self.backend.reset(sandbox)
                except Exception:  # noqa: BLE001 — recycle is best-effort
                    logger.exception("sandbox %s reset failed", sandbox.id)
                if recycled is not None:
                    # /reset wiped every host's workspace: the manifest
                    # cache restarts empty-known for the next generation
                    # (a stale entry would wrongly skip an upload).
                    self._transfer_state(recycled).reset()
                # Concurrent releases race the pool-short check above (all
                # pass it before any appends) — re-check after the await and
                # dispose the surplus, or a burst would leave the pool
                # permanently over target.
                if recycled is not None and not (
                    self._pool_supply(lane)
                    < self._lane_target(lane, extra_free=extra_free)
                    and not self._closed
                ):
                    recycled = None
            if recycled is not None:
                recycled.meta["pooled_at"] = self.scheduler.now()
                self._pool(lane).append(recycled)
                self.metrics.recycles.inc()
                self._notify_lane(lane)
            else:
                await self._dispose(sandbox)
        finally:
            if recycled is None:
                self.fill_pool_soon(lane)

    async def _dispose(self, sandbox: Sandbox) -> None:
        self._live_sandboxes.pop(sandbox.id, None)
        if self._store_shared:
            try:
                self.state_store.delete("hosts", sandbox.id)
            except Exception:  # noqa: BLE001
                logger.warning("host registry drop failed", exc_info=True)
        try:
            await self.backend.delete(sandbox)
        except Exception:  # noqa: BLE001
            logger.exception("failed to delete sandbox %s", sandbox.id)

    # ----------------------------------------------------------------- admin

    def live_hosts(self) -> list[tuple[int, Sandbox]]:
        """Every live sandbox with its lane — the device-health probe's
        inventory. Pooled, in-use, and session-parked sandboxes alike: the
        in-use ones are where mid-device-op wedges actually happen."""
        return list(self._live_sandboxes.values())

    def live_sandbox(self, sandbox_id: str) -> tuple[int, Sandbox] | None:
        """(lane, sandbox) for a live id, or None once disposed."""
        return self._live_sandboxes.get(sandbox_id)

    def statusz(self) -> dict:
        """The consolidated operator snapshot behind GET /statusz: one JSON
        joining what previously took a Prometheus query, a /healthz read,
        N sandbox ssh sessions, and the onchip_watch.sh grep loop — lanes
        (queue pressure, pool depth, occupancy, breaker), hosts with their
        device-health verdicts, sessions, compile-cache store state, and
        the telemetry plane's own health (probe liveness, OTLP backlog)."""
        lanes: dict[str, dict] = {}
        lane_ids = (
            set(self._pools)
            | set(self._in_use)
            | set(self._session_held)
            | set(self._spawning)
        )
        detail = self.scheduler.lane_detail()
        lane_ids |= {int(lane) for lane in detail}
        breaker_states = self.breakers.states()
        for lane in sorted(lane_ids):
            entry: dict = {
                "pool_depth": len(self._pools.get(lane, ())),
                # Supply vs its target: pooled counts only non-wedged
                # hosts (pool_depth - pooled = zombies awaiting fencing),
                # pool_target is the autoscaler's capacity-clamped verdict.
                "pooled": self._pool_supply(lane),
                "pool_target": self._lane_target(lane),
                "in_use": self._in_use.get(lane, 0),
                "session_held": self._session_held.get(lane, 0),
                "spawning": self._spawning.get(lane, 0),
                "breaker": breaker_states.get(lane, "closed"),
            }
            entry.update(detail.get(str(lane), {}))
            lanes[str(lane)] = entry
        status = "ok"
        if self._draining:
            status = "draining"
        elif self.degraded():
            status = "degraded"
        body: dict = {
            "status": status,
            "inflight": self.inflight(),
            "lanes": lanes,
            "sessions": self.list_sessions(),
            # The durability plane: hibernated-session count (records a
            # next turn would restore), checkpoint admit/restore/conflict
            # totals, and the idle cost the plane exists to kill —
            # cumulative chip-seconds spent parked-idle across sessions.
            "session_durability": {
                **self.session_store.snapshot(),
                "idle_chip_seconds_total": round(self._idle_chip_seconds, 3),
            },
            "batching": {
                "enabled": self.batcher is not None,
                "window_ms": self.config.batch_window_ms,
                "max_jobs": self.config.batch_max_jobs,
            },
            "compile_cache": {
                "enabled": self.compile_cache.enabled,
                "entries": self.compile_cache.entry_count(),
                "bytes": self.compile_cache.total_bytes(),
            },
            # The warm-pool autoscaler's verdicts next to the demand
            # signals driving them (per-lane targets, arrival rates,
            # scale/reap counts; just the config echo when disabled).
            "autoscaler": self.autoscaler.snapshot(),
            # The metering plane's own view: per-tenant cumulative counters
            # plus ledger health (flushes, journal lines, tenant-table
            # occupancy). Bounded — the tenant table caps at
            # APP_USAGE_MAX_TENANTS with an _overflow row.
            "usage": self.usage.snapshot(),
            # The quota layer's verdict state: per-tenant window
            # consumption vs budget, in-flight counts, quarantine
            # sentences, and denial totals — the "who is being shed, and
            # why" view next to the usage it is computed from.
            "quotas": self.quotas.snapshot(),
            # The performance anomaly plane: per-(lane, phase) drift
            # verdicts with their quantiles and baselines, tenant latency
            # series, and the auto-profiling/profile-store state — "did
            # anything get slower than it used to be, and is there a
            # profile of it yet?".
            "perf": self.perf.snapshot(),
        }
        if self.device_health is not None:
            body["device_health"] = self.device_health.snapshot()
        else:
            body["device_health"] = {"enabled": False}
        # The wedge-recovery actuation state: lease generations per scope,
        # in-flight re-admission streaks, fence/readmission totals, and
        # the actuation budget — "is the detect→act loop closing, and is
        # anything quarantined right now?".
        body["recovery"] = {
            "fencing_enabled": self.config.device_fence_enabled,
            "fence_budget": {
                "max_per_window": self.config.device_fence_max_per_window,
                "window_seconds": self.config.device_fence_window_seconds,
            },
            **self.leases.snapshot(),
        }
        if self.otlp_exporter is not None:
            body["otlp"] = {"enabled": True, **self.otlp_exporter.stats()}
        else:
            body["otlp"] = {"enabled": False}
        # The scale-out view: which replica this is, who is on the ring,
        # and how much traffic was proxied/redirected to session owners.
        if self.session_router is not None:
            body["replicas"] = {"enabled": True, **self.session_router.snapshot()}
        elif self._store_shared:
            body["replicas"] = {
                "enabled": True,
                "self": self.replica_id,
                "store": type(self.state_store).__name__,
            }
        # The store-loss plane: the resilient wrapper's breaker verdict,
        # outage/degraded-op counters, and quota-journal backlog — "are we
        # serving from the shared store or from replica-local fallbacks?".
        store_health = getattr(self.state_store, "health", None)
        if callable(store_health):
            body["state_store"] = store_health()
        return body

    async def sweep_pool_health(self) -> int:
        """Probe every pooled sandbox's /healthz and dispose the
        unresponsive ones (refilling their lanes). Proactive failure
        detection: a pooled sandbox whose process died silently (OOM kill,
        node trouble) would otherwise cost the next request a failed
        attempt before the retry path replaced it. Returns disposed count."""
        client = self._http_client()
        removed = 0

        async def probe(url: str) -> bool:
            try:
                resp = await client.get(f"{url}/healthz", timeout=3.0)
                return resp.status_code == 200
            except Exception:  # noqa: BLE001 — unreachable = dead
                return False

        for lane, pool in list(self._pools.items()):
            for sandbox in list(pool):
                # Probe a sandbox's hosts concurrently: serialized 3s
                # timeouts across a multi-host slice would make one sweep
                # take minutes on a hung node.
                if all(
                    await asyncio.gather(
                        *(probe(url) for url in sandbox.host_urls)
                    )
                ):
                    continue
                try:
                    pool.remove(sandbox)
                except ValueError:
                    continue  # popped by a request while we probed
                logger.warning(
                    "pooled sandbox %s failed its health probe; disposing",
                    sandbox.id,
                )
                removed += 1
                # Dispose off the sweep path via the tracked-task pattern:
                # close() AWAITS _dispose_tasks (it CANCELS the sweeper
                # itself, and a cancel landing mid-teardown would leak the
                # sandbox's process past the loop).
                task = asyncio.get_running_loop().create_task(
                    self._dispose(sandbox)
                )
                self._dispose_tasks.add(task)
                task.add_done_callback(self._dispose_tasks.discard)
                self.fill_pool_soon(lane)
        return removed

    def start_health_sweeper(self, interval: float) -> asyncio.Task | None:
        """Run sweep_pool_health every `interval` seconds until close()."""
        return self._start_sweeper(
            self.sweep_pool_health, interval, "pool health sweep"
        )

    # ------------------------------------------------------------ autoscaling

    async def autoscale_sweep(self) -> int:
        """One autoscaler pass over every known lane: run the scale-down
        hysteresis, start spawn-ahead refills where demand says supply will
        lag, and reap excess idle warm sandboxes so shared chip capacity
        migrates to pressured lanes. Returns the number reaped."""
        if self._closed:
            return 0
        if self._store_shared:
            # Cross-replica supply is invisible to the event-driven kicks
            # (a PEER's release frees capacity this replica's waiters are
            # parked on): the sweep doubles as the bounded-staleness
            # refresh — republish our occupancy gauges and wake every
            # lane's head so it re-evaluates against the peers' current
            # holds.
            for lane in self._known_lanes():
                self._publish_occupancy(lane)
            self.scheduler.kick_all()
        if not self.autoscaler.enabled:
            return 0
        reaped = 0
        for lane in self._known_lanes():
            snapshot = self._lane_snapshot(lane)
            self.autoscaler.evaluate(lane, snapshot)
            target = self._lane_target(lane)
            in_use = (
                snapshot.in_use if self.config.executor_reuse_sandboxes else 0
            )
            if (
                snapshot.pooled + snapshot.spawning + snapshot.recovering
                + in_use < target
                and not self.breakers.is_open(lane)
            ):
                # Spawn-ahead: the target says this lane needs more warm
                # supply than it has (or will shortly have) — refill NOW,
                # before a request is waiting on the gap.
                self.fill_pool_soon(lane)
            reaped += self._reap_idle(lane, target)
        return reaped

    def _reap_idle(self, lane: int, target: int) -> int:
        """Dispose warm pooled sandboxes above the lane target that have
        sat idle past pool_idle_reap_seconds (oldest first). Only healthy
        hosts are considered on BOTH sides — wedged zombies neither count
        as the supply being trimmed nor get disposed here (that is the
        fencing layer's actuation, not the autoscaler's)."""
        pool = self._pools.get(lane)
        if not pool:
            return 0
        excess = self._pool_supply(lane) - max(0, target)
        if excess <= 0:
            return 0
        now = self.scheduler.now()
        idle_after = self.config.pool_idle_reap_seconds
        candidates = sorted(
            (
                sandbox
                for sandbox in pool
                if sandbox.meta.get("device_health")
                not in self._UNSERVABLE_HEALTH
                and now - float(sandbox.meta.get("pooled_at", now))
                >= idle_after
            ),
            key=lambda s: float(s.meta.get("pooled_at", now)),
        )
        reaped = 0
        for sandbox in candidates[:excess]:
            try:
                pool.remove(sandbox)
            except ValueError:
                continue  # popped by a request while we decided
            reaped += 1

            async def reap_one(victim: Sandbox) -> None:
                await self._dispose(victim)
                # The freed slot may be what a pressured CONSTRAINED lane
                # is waiting on — wake every lane's head, the shared-
                # substrate discipline of _notify_all_lanes.
                self._notify_all_lanes()

            task = asyncio.get_running_loop().create_task(reap_one(sandbox))
            self._dispose_tasks.add(task)
            task.add_done_callback(self._dispose_tasks.discard)
        if reaped:
            logger.info(
                "autoscale reap: disposed %d idle sandbox(es) on lane %d "
                "(target %d)",
                reaped,
                lane,
                target,
            )
            self.autoscaler.note_reaped(lane, reaped)
        return reaped

    def start_autoscaler(self, interval: float | None = None) -> asyncio.Task | None:
        """Run autoscale_sweep periodically until close(). None (no loop)
        with the kill switch on or a zero interval — targets then only
        ever move UP, on arrivals, and nothing is reaped. With a SHARED
        state store the loop still runs (even autoscale-disabled): it is
        the bounded-staleness refresh that re-publishes occupancy and
        wakes waiters parked behind a peer's since-released capacity."""
        if not self.autoscaler.enabled and not self._store_shared:
            return None
        if interval is None:
            interval = self.config.pool_autoscale_interval
        return self._start_sweeper(
            self.autoscale_sweep, interval, "autoscale sweep"
        )

    def lane_supply(self) -> dict[str, dict]:
        """Per-lane SUPPLY joined into GET /healthz next to the demand
        stats it already shows (queue depth / wait EWMA): the dynamic pool
        target and what currently backs it — so an operator can see supply
        next to the signals driving it without a /statusz round-trip.
        With the probe daemon attached, each row also carries the lane's
        device-health census (healthy/busy/suspect/wedged/recovering/
        draining counts — the wedge-recovery satellite: a fenced lane's
        quarantine is visible exactly where its queue pressure is)."""
        census: dict[int, dict[str, int]] = {}
        if self.device_health is not None:
            census = self.device_health.lane_census()
        rows: dict[str, dict] = {}
        for lane in sorted(self._known_lanes() | set(census)):
            row: dict = {
                "pool_target": self._lane_target(lane),
                "pooled": self._pool_supply(lane),
                "in_use": self._in_use.get(lane, 0),
                "spawning": self._spawning.get(lane, 0),
            }
            recovering = self._pool_standby(lane)
            draining = self._draining_count(lane)
            if recovering:
                row["recovering"] = recovering
            if draining:
                row["draining"] = draining
            if lane in census:
                row["device_health"] = census[lane]
            rows[str(lane)] = row
        return rows

    def start_compile_cache_prewarm(self) -> asyncio.Task | None:
        """Pre-warm the fleet compile-cache store from the examples/ kernel
        set (distilled: matmul/elementwise/reduction) after pool fill.

        Strictly a background nicety with attach-budget hygiene (the
        device-health roadmap discipline — a primer must never block a
        serving path): runs at `batch` priority so interactive work always
        outranks it, and while real work is queued on the lane it waits
        out the backlog (30s backoff) rather than occupying a slot —
        pre-warm is the store's only admission source, so it never gives
        up just because the lane is busy. It runs on EVERY control-plane
        start, warm persisted index or not:
        pre-warm runs are the store's only admission source, so this is
        where an evicted-but-still-prewarmed kernel gets re-admitted (one
        trusted recompile, with fresh recency). Surviving entries are NOT
        refreshed by the pass — they get seeded into the pre-warm sandbox,
        and harvest deliberately ignores seeded entries' re-observation
        (see SandboxCacheSync.harvest_host) — so on a warm store the
        sandboxes compile nothing and the whole pass costs a few
        batch-priority executes."""
        if not (
            self.config.compile_cache_enabled
            and self.config.compile_cache_prewarm
            and self.compile_cache.enabled
        ):
            return None
        if self._compile_cache_dir_scope() == "external":
            # Harvest is structurally off (shared PVC/hostPath volume:
            # nothing can vouch for the dir), so no pre-warm pass could
            # ever admit anything — running one would burn TPU time on
            # kernels whose artifacts the store must refuse, then warn
            # about an empty store as if something had failed.
            logger.info(
                "compile-cache pre-warm skipped: the backend's cache dir "
                "is externally writable, so harvest (the store's only "
                "admission source) is disabled"
            )
            return None
        if self._prewarm_started:
            return None
        self._prewarm_started = True
        task = asyncio.get_running_loop().create_task(
            self._prewarm_compile_cache()
        )
        self._fill_tasks.add(task)  # cancelled/awaited by close()
        task.add_done_callback(self._fill_tasks.discard)
        return task

    async def _execute_trusted(self, source_code: str, **kwargs) -> Result:
        """Run CONTROL-PLANE-AUTHORED code through the normal execute path
        without tainting the sandbox's compile-cache provenance — the only
        way a sandbox stays harvest-eligible (see _run_on_sandbox). Callers
        must pass literal, control-plane-owned source: anything derived from
        tenant input would reopen the cache-poisoning channel the taint
        exists to close."""
        token = _trusted_source_var.set(True)
        try:
            return await self.execute(source_code, **kwargs)
        finally:
            _trusted_source_var.reset(token)

    # Backoff between pre-warm attempts while real work is queued on the
    # lane, and between retries of an ineffective pass. Class attribute so
    # tests can shrink it.
    _PREWARM_BACKOFF_SECONDS = 30.0
    # A pass whose kernels all ran yet admitted NOTHING (store still empty)
    # landed on tainted recycled sandboxes — under sustained load with
    # reuse on, the pool can hold only tenant-tainted sandboxes, and a
    # trusted run there compiles fine but is harvest-ineligible. Retrying
    # gives the untainted-preference pool pop (_pop_pool_sandbox) fresh
    # spawns to land on; bounded so a deployment whose only sandbox is
    # tainted for life degrades to a loud warning, not an infinite loop.
    _PREWARM_MAX_PASSES = 5

    async def _prewarm_compile_cache(self) -> None:
        lane = self.config.default_chip_count
        for attempt in range(self._PREWARM_MAX_PASSES):
            if attempt:
                await asyncio.sleep(self._PREWARM_BACKOFF_SECONDS)
                if self._closed or self._draining:
                    return
            if (
                self._compile_cache_dir_scope() == "shared"
                and self._shared_cache_tainted
            ):
                # Tenant code beat the pre-warm to the shared cache dir:
                # the taint is control-plane-lifetime, so no later pass
                # can ever admit anything — retrying would just burn
                # sandbox time warning about it.
                logger.warning(
                    "compile-cache pre-warm stopped: tenant code already "
                    "ran against the shared cache dir, so harvest is off "
                    "for this control plane's lifetime (store has %d "
                    "entries)",
                    self.compile_cache.entry_count(),
                )
                return
            warmed = await self._prewarm_pass(lane)
            if warmed is None:
                return  # shutdown, or a kernel failed: retrying won't help
            # Harvest runs inside the release task execute() fires in its
            # finally (off the request hot path), so the last kernel's
            # admissions may still be in flight when the pass returns —
            # let in-flight releases settle before judging the pass by
            # the store's contents.
            pending = [t for t in self._dispose_tasks if not t.done()]
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            if self.compile_cache.entry_count() > 0:
                logger.info(
                    "compile-cache pre-warm complete: %d kernels, store "
                    "holds %d entries (%d bytes)",
                    warmed,
                    self.compile_cache.entry_count(),
                    self.compile_cache.total_bytes(),
                )
                return
            logger.warning(
                "compile-cache pre-warm pass %d ran %d kernels but admitted "
                "nothing (tainted sandboxes or harvest failures); retrying",
                attempt + 1,
                warmed,
            )
        logger.warning(
            "compile-cache pre-warm gave up after %d ineffective passes: "
            "the fleet store is empty and has no other admission source",
            self._PREWARM_MAX_PASSES,
        )

    async def _prewarm_pass(self, lane: int) -> int | None:
        """One trusted run of every pre-warm kernel. Returns the number of
        kernels that ran, or None when the pass should never be retried
        (shutdown, or a kernel itself failed — e.g. jax missing from the
        sandbox image)."""
        warmed = 0
        for name, source in PREWARM_SOURCES:
            waiting_logged = False
            while self.scheduler.queued(lane) > 0:
                # Real requests are waiting for this lane: don't occupy a
                # sandbox slot for priming — wait for a quiet moment
                # instead of aborting forever. Pre-warm runs are the fleet
                # store's ONLY admission source, so a control plane
                # restarted under sustained load would otherwise serve its
                # whole lifetime with an empty store, recompiling every
                # kernel on every spawn. Logged once per wait, not per
                # 30s poll — sustained load would otherwise turn this
                # into an unbounded periodic log line.
                if not waiting_logged:
                    logger.info(
                        "compile-cache pre-warm waiting: lane-%d has "
                        "queued work",
                        lane,
                    )
                    waiting_logged = True
                await asyncio.sleep(self._PREWARM_BACKOFF_SECONDS)
                if self._closed or self._draining:
                    return None
            if self._closed or self._draining:
                return None
            try:
                result = await self._execute_trusted(source, priority="batch")
            except Exception as e:  # noqa: BLE001 — prewarm must never crash
                logger.warning(
                    "compile-cache pre-warm kernel %s failed: %r", name, e
                )
                return None
            if result.exit_code != 0:
                # e.g. jax missing in the sandbox image: pointless to
                # continue (and harmless to stop).
                logger.info(
                    "compile-cache pre-warm kernel %s exited %d; stopping",
                    name,
                    result.exit_code,
                )
                return None
            warmed += 1
        return warmed

    async def close(self) -> None:
        self._closed = True
        # Batching first: pending windows fail their futures (retryable),
        # in-flight dispatch tasks finish (they own sandbox release).
        if self.batcher is not None:
            await self.batcher.close()
        # Cancel in-flight pool refills — a spawn can take tens of seconds
        # (TPU warm-up) and shutdown must not wait for it; the backend kills
        # half-spawned sandboxes because they register before readiness.
        fills = list(self._fill_tasks)
        for task in fills:
            task.cancel()
        # Disposals run to completion so no subprocess outlives the loop.
        pending = fills + list(self._dispose_tasks)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        sandboxes = [s for pool in self._pools.values() for s in pool]
        self._pools.clear()
        # Session sandboxes die with the service: sessions are affinity to a
        # live process, not durable state (files round-tripped through
        # Storage are what survives restarts — the reference's model).
        for session in self._sessions.values():
            if session.sandbox is not None and not session.closed:
                session.closed = True
                sandboxes.append(session.sandbox)
        self._sessions.clear()
        self._session_held.clear()
        await asyncio.gather(*(self._dispose(s) for s in sandboxes))
        self._live_sandboxes.clear()
        # The hot set survives restarts through the persisted index (the
        # per-harvest saves make this a formality, but a clean shutdown
        # should never depend on the last harvest having had new entries).
        self.compile_cache.save_index()
        # Final ledger flush: a clean shutdown loses ZERO attribution (the
        # flush-interval bound is for crashes only).
        self.usage.close()
        if self._client is not None and not self._client.is_closed:
            await self._client.aclose()
        await self.backend.close()
        # Retire this replica's shared-state footprint: peers must not
        # keep subtracting a dead replica's occupancy until the TTL ages
        # it out when the shutdown was orderly.
        if self._store_shared:
            try:
                for lane in list(
                    set(self._in_use) | set(self._session_held) | set(self._spawning)
                ):
                    self.state_store.delete(
                        "occupancy", f"{lane}/{self.replica_id}"
                    )
                self.state_store.delete("replicas", self.replica_id)
            except Exception:  # noqa: BLE001
                logger.warning("shared-state retirement failed", exc_info=True)
        self.state_store.close()
