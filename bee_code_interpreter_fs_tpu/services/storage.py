"""Content-addressed object storage for workspace file round-trips.

Files flowing into/out of executions and through the ``/v1/files`` API live
here, keyed by the SHA-256 of their content. This fixes the reference's lie
(its docstring claims content addressing but names objects with
``secrets.token_hex(32)`` — src/code_interpreter/services/storage.py:36-52,
SURVEY.md §0.3): real content addressing is what makes the delta workspace
sync possible — the object id IS the content sha, so the executor's
per-workspace manifest (executor/server.cpp) and this store negotiate by
hash and unchanged files never cross the wire twice (services/transfer.py).

API shape parity: async streaming ``writer()``/``reader()`` context managers
and whole-object ``write/read/exists/delete`` (storage.py:44-101), with ids
kept opaque to clients.
"""

from __future__ import annotations

import hashlib
import os
import secrets
from contextlib import asynccontextmanager
from pathlib import Path

import anyio

from ..utils.validation import OBJECT_ID_RE, SHA256_HEX_RE

CHUNK_SIZE = 1 << 20


class StorageObjectNotFound(KeyError):
    pass


class _HashingWriter:
    """File sink that hashes content as it streams in.

    The final object id is available as ``.hash`` only after the surrounding
    context manager exits (matching the reference writer's contract where the
    id is assigned up-front; here it can't be, because the id IS the digest).
    """

    def __init__(self, file: anyio.AsyncFile) -> None:
        self._file = file
        self._digest = hashlib.sha256()
        self.size = 0
        self.hash: str | None = None

    async def write(self, data: bytes) -> None:
        self._digest.update(data)
        self.size += len(data)
        await self._file.write(data)

    def _finalize(self) -> str:
        self.hash = self._digest.hexdigest()
        return self.hash


class Storage:
    def __init__(self, storage_path: str | os.PathLike) -> None:
        self.path = Path(storage_path)
        self.path.mkdir(parents=True, exist_ok=True)
        self._tmp = self.path / ".tmp"
        self._tmp.mkdir(exist_ok=True)

    def _object_path(self, object_id: str) -> Path:
        if not OBJECT_ID_RE.match(object_id):
            raise ValueError(f"invalid object id: {object_id!r}")
        return self.path / object_id

    @asynccontextmanager
    async def writer(self):
        """Stream an object in; its content hash becomes the object id."""
        tmp_path = self._tmp / secrets.token_hex(16)
        async with await anyio.open_file(tmp_path, "wb") as f:
            w = _HashingWriter(f)
            try:
                yield w
            except BaseException:
                await anyio.Path(tmp_path).unlink(missing_ok=True)
                raise
        object_id = w._finalize()
        final = self.path / object_id
        if await anyio.Path(final).exists():
            # Dedup: identical content already stored.
            await anyio.Path(tmp_path).unlink(missing_ok=True)
        else:
            os.replace(tmp_path, final)
        assert SHA256_HEX_RE.match(object_id), object_id

    @asynccontextmanager
    async def reader(self, object_id: str):
        p = self._object_path(object_id)
        try:
            f = await anyio.open_file(p, "rb")
        except FileNotFoundError:
            raise StorageObjectNotFound(object_id) from None
        async with f:
            yield f

    async def write(self, data: bytes) -> str:
        async with self.writer() as w:
            await w.write(data)
        assert w.hash is not None
        return w.hash

    async def read(self, object_id: str) -> bytes:
        async with self.reader(object_id) as f:
            return await f.read()

    async def exists(self, object_id: str) -> bool:
        return await anyio.Path(self._object_path(object_id)).exists()

    async def size(self, object_id: str) -> int:
        try:
            stat = await anyio.Path(self._object_path(object_id)).stat()
        except FileNotFoundError:
            raise StorageObjectNotFound(object_id) from None
        return stat.st_size

    async def delete(self, object_id: str) -> None:
        await anyio.Path(self._object_path(object_id)).unlink(missing_ok=True)
