"""Performance anomaly plane: latency baselines, drift verdicts, profiles.

The third observability plane, alongside device-health (PR 8) and usage
metering (PR 9). Those answer "is the hardware alive?" and "who consumed
what?"; nothing answered "did the service get SLOWER than it used to be?"
— a 3x latency regression on one lane's hot kernel was invisible until a
human read histograms. This module turns the existing per-request phase
timings into standing verdicts:

- **Streaming latency baselines** — per-(lane, phase) and per-tenant
  p50/p95/p99 via bounded streaming quantile sketches (dep-free,
  fake-clock injectable). Each series keeps a cumulative sketch (the
  /perf quantile read) and a per-window sketch that rolls every
  ``APP_PERF_WINDOW_SECONDS``.
- **EWMA-banded drift detection** — each closed window's drift quantile
  is compared against an EWMA baseline learned from NORMAL windows only
  (a regression must not poison the baseline it is measured against) and
  classified ``normal | degraded | regressed``. Transitions touching
  ``regressed`` emit a head-sampling-proof ``perf.regression``
  record_span (the device-health transition discipline) and fire
  ``perf_regression_total{lane,phase}``.
- **Auto-triggered profiling** — a regressed (lane, phase) verdict, or a
  single request landing past the cumulative p99 band, ARMS the JAX
  profiler for the next matching request whose tenant has not opted out
  (``APP_PERF_PROFILE_TENANT_OPT_OUT``). The executor harvests the
  resulting profile.zip into the bounded content-addressed
  :class:`ProfileStore` (LRU by last access, byte/entry caps, persisted
  index — the compile-cache store discipline), retrievable via
  ``GET /profiles`` with trace-id cross-links. Control-plane-induced
  captures bill ZERO transfer bytes (the PR 9 trusted-run rule).

Cardinality discipline: lane×phase series are naturally bounded (lanes ×
the four latency phases) and additionally capped by
``APP_PERF_MAX_SERIES``; tenant series cap at ``APP_PERF_MAX_TENANTS``
with an ``_overflow`` row — the scheduler/ledger/device-health rule.

Kill switch: ``APP_PERF_OBSERVER_ENABLED=0`` constructs a disabled
observer — ``record``/``take_profile_arm`` no-op, no perf keys enter
Result.phases, the wire payload never asks sandboxes for device-memory
samples, ``/perf`` and ``/profiles`` answer 404, and no perf metric
family registers — today's behavior byte-for-byte.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import logging
import math
import os
import time
import uuid
from dataclasses import dataclass, field

from ..utils import tracing

logger = logging.getLogger(__name__)

NORMAL = "normal"
DEGRADED = "degraded"
REGRESSED = "regressed"
PERF_STATES = (NORMAL, DEGRADED, REGRESSED)

OVERFLOW_TENANT = "_overflow"

# The latency phases worth baselining: the Result.phases allowlist keys
# (services/code_executor.py LATENCY_PHASES). Anything else in phases is a
# byte count or coordinate, not a latency.
OBSERVED_PHASES = ("queue_wait", "upload", "exec", "download")


class StreamingQuantile:
    """Bounded streaming quantile sketch over geometric log-buckets.

    Values land in buckets at geometric boundaries
    ``min_value * growth**i``; a quantile read walks the cumulative counts
    and answers the bucket's geometric midpoint. Memory is a fixed array
    of ``max_buckets`` ints per sketch — no sample retention, no heap
    growth with traffic — and the relative error is bounded by the bucket
    growth factor (~4% at the default 1.08). Deterministic: the same value
    stream always produces the same quantiles, which is what makes the
    drift detector's verdicts replayable in tests and chaos legs.
    """

    __slots__ = ("min_value", "_log_growth", "max_buckets", "counts",
                 "count", "sum", "max_value", "_underflow")

    def __init__(
        self,
        min_value: float = 1e-4,
        growth: float = 1.08,
        max_buckets: int = 256,
    ) -> None:
        self.min_value = max(1e-9, float(min_value))
        self._log_growth = math.log(max(1.000001, float(growth)))
        self.max_buckets = max(8, int(max_buckets))
        self.counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.max_value = 0.0
        self._underflow = 0  # values at/below min_value

    def add(self, value: float) -> None:
        if not isinstance(value, (int, float)) or value != value or value < 0:
            return
        value = float(value)
        self.count += 1
        self.sum += value
        if value > self.max_value:
            self.max_value = value
        if value <= self.min_value:
            self._underflow += 1
            return
        index = min(
            self.max_buckets - 1,
            int(math.log(value / self.min_value) / self._log_growth) + 1,
        )
        self.counts[index] = self.counts.get(index, 0) + 1

    def quantile(self, q: float) -> float:
        """The q-quantile estimate (0 <= q <= 1), 0.0 on an empty sketch."""
        if self.count <= 0:
            return 0.0
        rank = max(1, math.ceil(min(1.0, max(0.0, q)) * self.count))
        if rank <= self._underflow:
            return self.min_value
        seen = self._underflow
        for index in sorted(self.counts):
            seen += self.counts[index]
            if seen >= rank:
                if index >= self.max_buckets - 1:
                    # Overflow bucket: the observed max is the honest answer.
                    return self.max_value
                lower = self.min_value * math.exp((index - 1) * self._log_growth)
                upper = self.min_value * math.exp(index * self._log_growth)
                return (lower + upper) / 2.0
        return self.max_value

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


@dataclass
class SeriesState:
    """One latency series (a (lane, phase) pair, or a tenant): cumulative
    quantiles for the /perf read, the rolling window sketch the drift
    detector classifies, and the EWMA baseline it classifies against."""

    key: str
    cumulative: StreamingQuantile = field(default_factory=StreamingQuantile)
    window: StreamingQuantile = field(default_factory=StreamingQuantile)
    window_start: float = 0.0
    windows: int = 0
    baseline: float | None = None  # EWMA of normal windows' drift quantile
    state: str = NORMAL
    state_since: float = 0.0
    last_window_value: float = 0.0
    regressions: int = 0

    def snapshot(self, quantiles: tuple[float, ...] = (0.5, 0.95, 0.99)) -> dict:
        row: dict = {
            "state": self.state,
            "count": self.cumulative.count,
            "windows": self.windows,
            "baseline_s": round(self.baseline, 6) if self.baseline else None,
            "last_window_s": round(self.last_window_value, 6),
            "regressions": self.regressions,
        }
        for q in quantiles:
            row[f"p{int(q * 100)}_s"] = round(self.cumulative.quantile(q), 6)
        return row


class ProfileStore:
    """Bounded content-addressed store for harvested profile artifacts.

    The compile-cache store discipline: bytes are content-addressed
    (SHA-256 of the zip; identical captures dedup to one object), entries
    evict LRU-by-last-access under byte AND entry caps, and a JSON index
    persists across restarts so ``GET /profiles`` survives a control-plane
    bounce. All IO is small and synchronous (profiles are a few hundred KB
    and arrive at regression cadence, not request cadence).
    """

    def __init__(
        self,
        directory: str,
        *,
        max_bytes: int = 256 << 20,
        max_entries: int = 256,
        walltime=time.time,
    ) -> None:
        self.dir = directory
        self.max_bytes = max(1 << 20, int(max_bytes))
        self.max_entries = max(1, int(max_entries))
        self.walltime = walltime
        # id -> meta dict; insertion order irrelevant (LRU via last_access).
        self._entries: dict[str, dict] = {}
        self.evictions = 0
        os.makedirs(self.dir, exist_ok=True)
        self._load_index()

    # ----------------------------------------------------------- persistence

    @property
    def index_path(self) -> str:
        return os.path.join(self.dir, "index.json")

    def _object_path(self, profile_id: str) -> str:
        return os.path.join(self.dir, f"{profile_id}.zip")

    def _load_index(self) -> None:
        try:
            with open(self.index_path, encoding="utf-8") as f:
                body = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError, OSError):
            return
        entries = body.get("entries")
        if not isinstance(entries, dict):
            return
        for profile_id, meta in entries.items():
            if not isinstance(meta, dict):
                continue
            # An index row whose bytes are gone is a stale pointer, not an
            # artifact — drop it rather than 500 the later GET.
            if os.path.exists(self._object_path(str(profile_id))):
                self._entries[str(profile_id)] = meta

    def _persist_index(self) -> None:
        # Multi-writer safety (two control-plane replicas sharing one
        # store volume): a whole-file rewrite would last-writer-wins a
        # concurrent peer's entries out of the index, stranding its zips
        # as unlisted orphans. Merge the on-disk index first — rows we
        # don't know, whose bytes exist, are a peer's live captures and
        # are adopted (both into the write and into this process's view,
        # so GET /profiles on any replica lists the fleet's captures).
        # The object files themselves are content-addressed tmp+rename
        # writes, so concurrent writers can never tear them.
        #
        # The merge read and the rename must be ONE critical section: a
        # peer persisting between them would have its newest entry merged
        # by nobody and clobbered by our rename (a lost update the merge
        # alone cannot prevent). flock serializes writers — correct on the
        # documented single-node store posture (the same bound as the
        # SQLite StateStore; flock does not span NFS reliably) and a
        # best-effort no-op where the FS refuses it.
        lock = None
        try:
            lock = open(os.path.join(self.dir, "index.lock"), "a")
            fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        except OSError:
            if lock is not None:
                lock.close()
            lock = None
        try:
            try:
                with open(self.index_path, encoding="utf-8") as f:
                    disk = json.load(f).get("entries")
                if isinstance(disk, dict):
                    for profile_id, meta in disk.items():
                        if (
                            str(profile_id) not in self._entries
                            and isinstance(meta, dict)
                            and os.path.exists(
                                self._object_path(str(profile_id))
                            )
                        ):
                            self._entries[str(profile_id)] = meta
            except (FileNotFoundError, json.JSONDecodeError, OSError):
                pass
            # UNIQUE tmp name per write: two processes sharing one tmp path
            # could truncate each other mid-write and rename a torn file
            # into place. A PID suffix is NOT unique across pods
            # (containerized replicas on a shared volume are typically all
            # PID 1) — use a random token.
            tmp = f"{self.index_path}.{uuid.uuid4().hex[:12]}.tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"version": 1, "entries": self._entries}, f,
                              sort_keys=True)
                os.replace(tmp, self.index_path)
            except OSError:
                logger.warning(
                    "profile store index persist failed", exc_info=True
                )
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        finally:
            if lock is not None:
                lock.close()

    # ------------------------------------------------------------------- api

    def add(self, data: bytes, meta: dict) -> str | None:
        """Store one artifact; returns its content-addressed id, or None
        when the bytes could not be made durable (full/unwritable volume)
        — the caller must NOT treat the artifact as captured then. A
        repeat capture with identical bytes refreshes the existing
        entry's recency and meta instead of duplicating the object."""
        profile_id = hashlib.sha256(data).hexdigest()[:32]
        now = self.walltime()
        entry = self._entries.get(profile_id)
        if entry is None:
            try:
                tmp = self._object_path(profile_id) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, self._object_path(profile_id))
            except OSError:
                logger.warning("profile store write failed", exc_info=True)
                return None
            entry = {"size_bytes": len(data), "captured_at": round(now, 3)}
            self._entries[profile_id] = entry
        entry.update({
            k: v for k, v in meta.items()
            if isinstance(k, str) and v is not None
        })
        entry["last_access"] = round(now, 3)
        self._evict()
        self._persist_index()
        return profile_id

    def get(self, profile_id: str) -> tuple[bytes, dict] | None:
        entry = self._entries.get(profile_id)
        if entry is None:
            return None
        try:
            with open(self._object_path(profile_id), "rb") as f:
                data = f.read()
        except OSError:
            # Bytes vanished under the index (operator rm): self-heal.
            self._entries.pop(profile_id, None)
            self._persist_index()
            return None
        entry["last_access"] = round(self.walltime(), 3)
        self._persist_index()
        return data, entry

    def list(self) -> list[dict]:
        """Every entry's meta (id included), newest capture first."""
        rows = [
            {"id": profile_id, **meta}
            for profile_id, meta in self._entries.items()
        ]
        rows.sort(key=lambda row: row.get("captured_at", 0.0), reverse=True)
        return rows

    def total_bytes(self) -> int:
        return sum(int(m.get("size_bytes", 0)) for m in self._entries.values())

    def entry_count(self) -> int:
        return len(self._entries)

    def _evict(self) -> None:
        while self._entries and (
            len(self._entries) > self.max_entries
            or self.total_bytes() > self.max_bytes
        ):
            victim = min(
                self._entries,
                key=lambda pid: self._entries[pid].get("last_access", 0.0),
            )
            self._entries.pop(victim, None)
            self.evictions += 1
            try:
                os.unlink(self._object_path(victim))
            except OSError:
                pass


def summarize_profile(data: bytes, *, top_n: int = 10) -> dict:
    """An xprof VERDICT instead of a raw zip: parse the JAX profiler
    artifact's trace-event JSON (``*.trace.json[.gz]`` members — the
    TensorBoard/Perfetto feed) and report what an operator actually asks a
    profile: which ops dominated, what share of the wall the device was
    busy, and where the big idle gaps sat. Stdlib-only (zipfile/gzip/json)
    — no xprof/TensorBoard dependency; artifacts without a parseable trace
    (or on an old jaxlib layout) degrade to a member listing, never a 500.

    Durations in the trace-event format are microseconds; everything here
    reports milliseconds."""
    import gzip
    import io
    import zipfile

    try:
        archive = zipfile.ZipFile(io.BytesIO(data))
        members = archive.namelist()
    except Exception:  # noqa: BLE001 — corrupt artifact, not a server error
        return {"verdict": "unparseable", "detail": "not a zip archive"}
    events: list[dict] = []
    parsed_member = None
    for name in members:
        if not name.endswith((".trace.json", ".trace.json.gz")):
            continue
        try:
            raw = archive.read(name)
            if name.endswith(".gz"):
                raw = gzip.decompress(raw)
            trace = json.loads(raw)
        except Exception:  # noqa: BLE001
            continue
        found = trace.get("traceEvents")
        if isinstance(found, list):
            events = [e for e in found if isinstance(e, dict)]
            parsed_member = name
            break
    if not events:
        return {
            "verdict": "unparseable",
            "detail": "no trace-event JSON member found",
            "members": members[:50],
        }
    # pid -> process name from the metadata events; device pids are the
    # ones the profiler labels with a device/TPU/GPU identity.
    process_names: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            args = e.get("args")
            if isinstance(args, dict):
                process_names[e.get("pid")] = str(args.get("name", ""))
    device_pids = {
        pid
        for pid, name in process_names.items()
        if any(tag in name.lower() for tag in ("device", "tpu", "gpu", "xla"))
    }
    ops: dict[str, list[float]] = {}
    device_spans: list[tuple[float, float]] = []
    t_min = math.inf
    t_max = -math.inf
    for e in events:
        if e.get("ph") != "X":
            continue
        ts = e.get("ts")
        dur = e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(
            dur, (int, float)
        ):
            continue
        t_min = min(t_min, float(ts))
        t_max = max(t_max, float(ts) + float(dur))
        on_device = not device_pids or e.get("pid") in device_pids
        if on_device:
            device_spans.append((float(ts), float(ts) + float(dur)))
            bucket = ops.setdefault(str(e.get("name", "?")), [0.0, 0.0])
            bucket[0] += float(dur)
            bucket[1] += 1.0
    if not device_spans or not math.isfinite(t_min):
        return {
            "verdict": "no complete events in trace",
            "member": parsed_member,
            "members": members[:50],
        }
    # Busy wall = the union of device spans (ops overlap across cores);
    # idle gaps are the holes in that union over the capture window.
    device_spans.sort()
    busy_us = 0.0
    gaps: list[tuple[float, float]] = []
    cur_start, cur_end = device_spans[0]
    for start, end in device_spans[1:]:
        if start <= cur_end:
            cur_end = max(cur_end, end)
            continue
        busy_us += cur_end - cur_start
        gaps.append((cur_end, start - cur_end))
        cur_start, cur_end = start, end
    busy_us += cur_end - cur_start
    span_us = max(t_max - t_min, 1e-9)
    total_op_us = sum(total for total, _count in ops.values()) or 1e-9
    gaps.sort(key=lambda g: g[1], reverse=True)
    top_ops = sorted(
        ops.items(), key=lambda item: item[1][0], reverse=True
    )[:top_n]
    busy_share = busy_us / span_us
    verdict = (
        f"device busy {busy_share:.0%} of the {span_us / 1e3:.1f}ms capture"
        + (
            f"; largest idle gap {gaps[0][1] / 1e3:.1f}ms"
            if gaps
            else "; no idle gaps"
        )
        + (f"; top op: {top_ops[0][0]}" if top_ops else "")
    )
    return {
        "verdict": verdict,
        "member": parsed_member,
        "span_ms": round(span_us / 1e3, 3),
        "device_busy_ms": round(busy_us / 1e3, 3),
        "device_op_wall_share": round(busy_share, 4),
        "top_ops": [
            {
                "name": name,
                "total_ms": round(total / 1e3, 3),
                "count": int(count),
                "share": round(total / total_op_us, 4),
            }
            for name, (total, count) in top_ops
        ],
        "idle_gaps": [
            {
                "offset_ms": round((start - t_min) / 1e3, 3),
                "duration_ms": round(length / 1e3, 3),
            }
            for start, length in gaps[:5]
        ],
    }


@dataclass
class ProfileArm:
    """One armed auto-profile: the next eligible request on `lane` runs
    with the JAX profiler on. Consumed exactly once."""

    lane: int
    reason: str
    armed_at: float
    source_key: str = ""


class PerfObserver:
    """Streaming latency baselines + drift verdicts + profiling triggers.

    All state mutation happens on the control plane's event loop (the
    scheduler/ledger discipline); windows roll LAZILY on record() — no
    daemon task, and an idle series simply keeps its last verdict (no
    data is not a regression). `clock` is injectable for fake-clock tests;
    `walltime` stamps spans and store entries.
    """

    def __init__(
        self,
        config=None,
        *,
        metrics=None,
        tracer=None,
        clock=time.monotonic,
        walltime=time.time,
    ) -> None:
        from ..config import Config

        self.config = config or Config()
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        self.walltime = walltime
        self.enabled = bool(self.config.perf_observer_enabled)
        self.window_s = max(0.05, self.config.perf_window_seconds)
        self.min_samples = max(1, self.config.perf_min_window_samples)
        self.alpha = min(1.0, max(0.01, self.config.perf_baseline_alpha))
        self.degraded_factor = max(1.0, self.config.perf_degraded_factor)
        self.regressed_factor = max(
            self.degraded_factor, self.config.perf_regressed_factor
        )
        self.drift_quantile = min(
            0.999, max(0.5, self.config.perf_drift_quantile)
        )
        # Absolute slack under every band: sub-millisecond phases jitter by
        # whole multiples without meaning anything — a "3x regression" on a
        # 0.2ms upload is scheduler noise, not an incident.
        self.min_band_s = max(0.0, self.config.perf_min_band_seconds)
        self.max_series = max(8, self.config.perf_max_series)
        self.max_tenants = max(1, self.config.perf_max_tenants)
        self.auto_profile = bool(self.config.perf_profile_auto)
        self.p99_factor = max(1.0, self.config.perf_p99_outlier_factor)
        self.profile_interval = max(
            0.0, self.config.perf_profile_min_interval_seconds
        )
        self._opt_out = {
            str(t) for t in (self.config.perf_profile_tenant_opt_out or ())
        }
        self._series: dict[tuple[int, str], SeriesState] = {}
        self._tenants: dict[str, SeriesState] = {}
        # lane -> pending arm (one per lane: a second trigger before the
        # first consumes just refreshes the reason).
        self._arms: dict[int, ProfileArm] = {}
        # lane -> last profile consumption (throttle: a standing regression
        # must not profile every request on the lane).
        self._last_profiled: dict[int, float] = {}
        self.profiles_captured = 0
        self.started_at = walltime()
        self.store: ProfileStore | None = None
        if not self.enabled:
            return
        base = self.config.perf_profile_store_path or os.path.join(
            self.config.file_storage_path, ".profiles"
        )
        self.store = ProfileStore(
            base,
            max_bytes=self.config.perf_profile_store_max_bytes,
            max_entries=self.config.perf_profile_store_max_entries,
            walltime=walltime,
        )

    # --------------------------------------------------------------- recording

    def record_request(
        self, lane: int, phases: dict, tenant: str | None = None
    ) -> None:
        """Fold one finished request's phase latencies into the baselines
        (the executor calls this once per LOGICAL request, serial and
        batched alike). Tenant series track end-to-end request latency
        (the phase sum) — the per-tenant SLO read."""
        if not self.enabled or not isinstance(phases, dict):
            return
        total = 0.0
        for phase in OBSERVED_PHASES:
            value = phases.get(phase)
            if isinstance(value, (int, float)) and value >= 0:
                total += float(value)
                self.record(lane, phase, float(value))
        if tenant is not None and total > 0:
            self._record_tenant(tenant, total)

    def record(self, lane: int, phase: str, seconds: float) -> None:
        """One latency sample for a (lane, phase) series: roll the window
        if due, classify, feed the sketches, and check the p99 band."""
        if not self.enabled:
            return
        key = (int(lane), str(phase))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                return  # bounded: past the cap new series are not tracked
            series = SeriesState(key=f"lane-{lane}/{phase}")
            series.window_start = self.clock()
            series.state_since = self.clock()
            self._series[key] = series
        self._roll_if_due(series, lane=lane, phase=phase)
        # The p99 outlier trigger reads the CUMULATIVE sketch BEFORE this
        # sample lands (a sample must not raise the very band it is
        # measured against).
        if (
            self.auto_profile
            and series.cumulative.count >= self.min_samples
        ):
            band = series.cumulative.quantile(0.99) * self.p99_factor
            if band > self.min_band_s and seconds > band:
                self.arm_profile(
                    lane,
                    reason=f"p99_outlier:{phase}",
                    source_key=series.key,
                )
        series.cumulative.add(seconds)
        series.window.add(seconds)

    def _record_tenant(self, tenant: str, seconds: float) -> None:
        label = tenant
        if label not in self._tenants and len(self._tenants) >= self.max_tenants:
            label = OVERFLOW_TENANT
        series = self._tenants.get(label)
        if series is None:
            series = SeriesState(key=f"tenant/{label}")
            series.window_start = self.clock()
            series.state_since = self.clock()
            self._tenants[label] = series
        self._roll_if_due(series)
        series.cumulative.add(seconds)
        series.window.add(seconds)

    # ----------------------------------------------------------- drift windows

    def _roll_if_due(
        self, series: SeriesState, *, lane: int | None = None,
        phase: str | None = None,
    ) -> None:
        now = self.clock()
        if now - series.window_start < self.window_s:
            return
        window = series.window
        series.window = StreamingQuantile()
        series.window_start = now
        if window.count < self.min_samples:
            # Too thin to judge — keep the standing verdict and baseline.
            return
        series.windows += 1
        value = window.quantile(self.drift_quantile)
        series.last_window_value = value
        baseline = series.baseline
        if baseline is None:
            # First full window IS the baseline; by definition normal.
            series.baseline = value
            self._transition(series, NORMAL, lane=lane, phase=phase,
                             window_value=value)
            return
        degraded_band = baseline * self.degraded_factor + self.min_band_s
        regressed_band = baseline * self.regressed_factor + self.min_band_s
        if value > regressed_band:
            state = REGRESSED
        elif value > degraded_band:
            state = DEGRADED
        else:
            state = NORMAL
        if state == NORMAL:
            # The baseline learns ONLY from normal windows: a standing
            # regression must be measured against the healthy past, not
            # slowly become the new normal.
            series.baseline = baseline + self.alpha * (value - baseline)
        self._transition(series, state, lane=lane, phase=phase,
                         window_value=value)

    def _transition(
        self, series: SeriesState, state: str, *, lane: int | None,
        phase: str | None, window_value: float,
    ) -> None:
        previous = series.state
        if state == previous:
            return
        series.state = state
        series.state_since = self.clock()
        # The device-health transition discipline: only transitions touching
        # trouble are incident material. normal<->degraded flips log at
        # INFO; anything touching REGRESSED gets the head-sampling-proof
        # span and (entering) the counter + an arm.
        touching_regressed = REGRESSED in (state, previous)
        logger.log(
            logging.WARNING if state == REGRESSED else logging.INFO,
            "perf drift: %s %s -> %s (window %s=%.4fs baseline=%.4fs)",
            series.key,
            previous,
            state,
            f"p{int(self.drift_quantile * 100)}",
            window_value,
            series.baseline or 0.0,
        )
        if not touching_regressed:
            return
        if self.tracer is not None:
            self.tracer.record_span(
                "perf.regression",
                trace_id=tracing.new_trace_id(),
                parent_id=None,
                start_unix=self.walltime(),
                duration_s=0.0,
                attributes={
                    "series": series.key,
                    "lane": lane if lane is not None else -1,
                    "phase": phase or "",
                    "from": previous,
                    "to": state,
                    "window_s": round(window_value, 6),
                    "baseline_s": round(series.baseline or 0.0, 6),
                },
                status="error" if state == REGRESSED else "ok",
            )
        if state != REGRESSED:
            return
        series.regressions += 1
        if self.metrics is not None and lane is not None:
            self.metrics.record_perf_regression(
                lane=str(lane), phase=phase or ""
            )
        if lane is not None:
            self.arm_profile(
                lane,
                reason=f"regression:{phase or series.key}",
                source_key=series.key,
            )

    # -------------------------------------------------------- profile arming

    def arm_profile(self, lane: int, *, reason: str, source_key: str = "") -> None:
        """Arm the JAX profiler for the next eligible request on `lane`.
        Throttled: within perf_profile_min_interval_seconds of the last
        consumed capture on the lane, new triggers are dropped (a standing
        regression would otherwise profile every request)."""
        if not self.enabled or not self.auto_profile:
            return
        now = self.clock()
        last = self._last_profiled.get(lane)
        if last is not None and now - last < self.profile_interval:
            return
        existing = self._arms.get(lane)
        if existing is not None:
            existing.reason = reason  # refresh, never queue a second
            return
        self._arms[lane] = ProfileArm(
            lane=lane, reason=reason, armed_at=now, source_key=source_key
        )
        logger.info("auto-profile armed (lane=%d, reason=%s)", lane, reason)

    def take_profile_arm(self, lane: int, tenant: str | None) -> str | None:
        """Consume the lane's pending arm for a CONSENTING tenant; returns
        the trigger reason, or None (nothing armed / tenant opted out — an
        opt-out tenant's request passes through untouched and the arm waits
        for the next eligible one)."""
        if not self.enabled or not self.auto_profile:
            return None
        arm = self._arms.get(lane)
        if arm is None:
            return None
        if tenant is not None and tenant in self._opt_out:
            return None
        del self._arms[lane]
        self._last_profiled[lane] = self.clock()
        return arm.reason

    def note_profile_captured(
        self, data: bytes, *, lane: int, reason: str,
        tenant: str | None = None, trace_id: str | None = None,
    ) -> str | None:
        """Harvest one auto-captured profile.zip into the store; returns
        the profile id (the /profiles/{id} handle), or None when the
        store could not make it durable — the caller then leaves the
        artifact in the request's files instead of destroying the only
        copy, and nothing counts as captured."""
        if not self.enabled or self.store is None:
            return None
        profile_id = self.store.add(
            data,
            {
                "lane": lane,
                "reason": reason,
                "tenant": tenant,
                "trace_id": trace_id,
            },
        )
        if profile_id is None:
            return None
        self.profiles_captured += 1
        if self.metrics is not None:
            self.metrics.record_perf_profile(reason=reason.split(":", 1)[0])
        logger.info(
            "auto-profile captured (lane=%d, reason=%s, id=%s, trace=%s)",
            lane, reason, profile_id, trace_id,
        )
        return profile_id

    # ---------------------------------------------------------------- surfaces

    def state_gauge_samples(self) -> dict[tuple[str, ...], float]:
        """perf_state{lane,phase,state} one-hot feed (scrape-time)."""
        samples: dict[tuple[str, ...], float] = {}
        for (lane, phase), series in self._series.items():
            for state in PERF_STATES:
                samples[(str(lane), phase, state)] = (
                    1.0 if series.state == state else 0.0
                )
        return samples

    def store_gauge_samples(self) -> dict[tuple[str, ...], float]:
        if self.store is None:
            return {}
        return {
            ("bytes",): float(self.store.total_bytes()),
            ("entries",): float(self.store.entry_count()),
        }

    def lane_phase_states(self) -> dict[str, str]:
        """{"<lane>/<phase>": state} — the tests' and /statusz's quick read."""
        return {
            f"{lane}/{phase}": series.state
            for (lane, phase), series in self._series.items()
        }

    def snapshot(self) -> dict:
        """The GET /perf body (and the /statusz perf section)."""
        body: dict = {
            "enabled": self.enabled,
            "window_seconds": self.window_s,
            "drift_quantile": self.drift_quantile,
            "bands": {
                "degraded_factor": self.degraded_factor,
                "regressed_factor": self.regressed_factor,
                "min_band_s": self.min_band_s,
            },
            "series": {},
            "tenants": {},
        }
        if not self.enabled:
            return body
        worst = NORMAL
        for (lane, phase), series in sorted(self._series.items()):
            body["series"][f"{lane}/{phase}"] = series.snapshot()
            if PERF_STATES.index(series.state) > PERF_STATES.index(worst):
                worst = series.state
        for tenant, series in sorted(self._tenants.items()):
            body["tenants"][tenant] = series.snapshot()
        body["status"] = worst
        body["auto_profile"] = {
            "enabled": self.auto_profile,
            "armed_lanes": sorted(
                {lane: arm.reason for lane, arm in self._arms.items()}.items()
            ),
            "captured": self.profiles_captured,
            "opt_out_tenants": sorted(self._opt_out),
        }
        if self.store is not None:
            body["profile_store"] = {
                "entries": self.store.entry_count(),
                "bytes": self.store.total_bytes(),
                "max_bytes": self.store.max_bytes,
                "max_entries": self.store.max_entries,
                "evictions": self.store.evictions,
            }
        return body
