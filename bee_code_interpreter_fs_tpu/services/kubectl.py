"""Async adapter over the ``kubectl`` CLI.

Same architectural choice as the reference (services/kubectl.py:25-28): the
CLI rather than the kubernetes Python client, because the CLI gives us
battle-tested auth/exec/wait behavior and composes with asyncio via
subprocesses. The reference exposed every subcommand through ``__getattr__``
magic with typing overloads (kubectl.py:99-178); here the surface is explicit
— the orchestrator uses exactly five verbs, and explicit methods are greppable
and typo-safe. kwargs become ``--key=value`` flags; dict stdin is sent as
JSON (kubectl.py:84-91); non-zero exit raises KubectlError with stderr
(kubectl.py:93-96).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any

logger = logging.getLogger(__name__)


class KubectlError(RuntimeError):
    def __init__(self, argv: list[str], returncode: int, stderr: str) -> None:
        super().__init__(
            f"kubectl {' '.join(argv)} failed with exit code {returncode}: {stderr.strip()}"
        )
        self.argv = argv
        self.returncode = returncode
        self.stderr = stderr


def _flags(kwargs: dict[str, Any]) -> list[str]:
    out = []
    for key, value in kwargs.items():
        flag = "--" + key.replace("_", "-")
        if value is True:
            out.append(flag)
        elif value is False:
            out.append(f"{flag}=false")
        elif value is not None:
            out.append(f"{flag}={value}")
    return out


class Kubectl:
    """Thin async kubectl runner; ctor kwargs (e.g. namespace) apply to every
    call, mirroring the reference's default-kwargs ctor (kubectl.py:40-46)."""

    def __init__(self, binary: str = "kubectl", **defaults: Any) -> None:
        self.binary = binary
        self.defaults = defaults

    async def _run(
        self,
        *argv: str,
        stdin: bytes | str | dict | list | None = None,
        **kwargs: Any,
    ) -> str:
        full = [*argv, *_flags({**self.defaults, **kwargs})]
        if isinstance(stdin, (dict, list)):
            stdin = json.dumps(stdin)
        if isinstance(stdin, str):
            stdin = stdin.encode()
        proc = await asyncio.create_subprocess_exec(
            self.binary,
            *full,
            stdin=asyncio.subprocess.PIPE if stdin is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        stdout, stderr = await proc.communicate(stdin)
        if proc.returncode != 0:
            raise KubectlError(full, proc.returncode, stderr.decode())
        return stdout.decode()

    async def _run_json(self, *argv: str, **kwargs: Any) -> Any:
        out = await self._run(*argv, output="json", **kwargs)
        return json.loads(out)

    # ------------------------------------------------------------- verbs

    async def get(self, kind: str, name: str | None = None, **kwargs: Any) -> Any:
        argv = ["get", kind] + ([name] if name else [])
        return await self._run_json(*argv, **kwargs)

    async def create(self, manifest: dict, **kwargs: Any) -> Any:
        return await self._run_json("create", "-f", "-", stdin=manifest, **kwargs)

    async def wait(self, kind: str, name: str, **kwargs: Any) -> str:
        return await self._run("wait", f"{kind}/{name}", **kwargs)

    async def delete(self, kind: str, name: str, **kwargs: Any) -> str:
        return await self._run(
            "delete", kind, name, ignore_not_found=True, **kwargs
        )

    async def logs(self, name: str, **kwargs: Any) -> str:
        return await self._run("logs", name, **kwargs)
