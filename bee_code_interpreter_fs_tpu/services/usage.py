"""Per-tenant usage metering: chip-second attribution with a durable ledger.

Before this module, tenants existed only as labels on rate counters — no
answer to "what did tenant X consume this month" survived a restart, and
the quota/abuse-control layer (services/quotas.py, which reads exactly
these counters at admission) had nothing to enforce against.
This is the billing-grade half of the ROADMAP's production-multi-tenancy
item: every request's consumption is attributed to its tenant and folded
into monotonic counters that persist across control-plane restarts.

What is metered (all cumulative, all monotonic):

- ``chip_seconds`` — chip_count x device-op wall time, from the executor's
  own op window (the ``device_op_seconds`` wire field; NOT control-plane
  wall, which includes queueing). Batched dispatches apportion the fused
  run's chip-seconds across the batch's jobs by their per-job exec spans
  (equal split when absent), so a tenant's bill is identical whether its
  jobs rode the fused or serial path. Requests that fault or violate a
  limit AFTER consuming device time are still billed.
- ``device_op_seconds`` — the un-multiplied op wall (chip_seconds without
  the chip factor; useful to sanity-check the multiplier).
- ``queue_wait_seconds`` — scheduler queue wait, attributed at grant time
  (a multi-job batch ticket bills its wait once per request it served).
- ``upload_bytes`` / ``download_bytes`` — transfer bytes actually MOVED
  (the PR 3 counters' moved-vs-skipped distinction: negotiated-away bytes
  cost nothing and bill nothing).
- ``compile_cache_recompiles`` / ``compile_cache_new_bytes`` — kernels the
  tenant's runs had to compile (persistent-cache misses) and the cache
  bytes those compilations produced.
- ``requests`` (+ per-``outcome`` counts) and ``batch_jobs``, plus typed
  limit ``violations`` by kind.

Durability: the in-memory table is the truth; every flush interval, each
dirty tenant appends ONE cumulative JSONL line to the journal
(latest-wins — replay is idempotent no matter where a crash landed), and
when the journal outgrows its bound a compaction rewrites the snapshot
(tmp+rename, atomic) and truncates the journal. A SIGKILL at any point
loses at most one flush interval of attribution; a torn tail line is
detected (bad JSON) and skipped.

Cardinality: the tenant table is bounded (``APP_USAGE_MAX_TENANTS``); past
the cap new tenants' usage accrues to one ``_overflow`` row — the same
discipline as the scheduler's metric-tenant cap and the device-health
host-label cap. The kill switch (``APP_USAGE_METERING_ENABLED=0``)
restores pre-metering behavior byte-for-byte: no ledger object state, no
journal IO, no metric samples, 404 on ``GET /usage``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

OVERFLOW_TENANT = "_overflow"

# Scalar counter fields, in render order. dict-valued counters (outcomes,
# violations) are handled alongside but keyed by their own label.
COUNTER_FIELDS = (
    "chip_seconds",
    "device_op_seconds",
    "queue_wait_seconds",
    "hbm_byte_seconds",
    "upload_bytes",
    "download_bytes",
    "compile_cache_recompiles",
    "compile_cache_new_bytes",
    "requests",
    "batch_jobs",
)


@dataclass
class TenantUsage:
    """One tenant's cumulative counters. Monotonic: nothing here ever
    decreases — merge-on-load takes the elementwise max, so replaying a
    journal over a snapshot (or a stale line after a newer one) is
    idempotent."""

    chip_seconds: float = 0.0
    device_op_seconds: float = 0.0
    queue_wait_seconds: float = 0.0
    # Device-memory attribution (the perf-observer plane): the request's
    # peak HBM footprint integrated over its device-op wall — the signal
    # that makes memory hogs attributable (and, later, quota-able) the way
    # chip_seconds makes compute hogs attributable.
    hbm_byte_seconds: float = 0.0
    upload_bytes: float = 0.0
    download_bytes: float = 0.0
    compile_cache_recompiles: float = 0.0
    compile_cache_new_bytes: float = 0.0
    requests: float = 0.0
    batch_jobs: float = 0.0
    outcomes: dict[str, float] = field(default_factory=dict)
    violations: dict[str, float] = field(default_factory=dict)

    def as_dict(self) -> dict:
        body: dict = {
            name: round(getattr(self, name), 6) for name in COUNTER_FIELDS
        }
        body["outcomes"] = {k: v for k, v in sorted(self.outcomes.items())}
        body["violations"] = {k: v for k, v in sorted(self.violations.items())}
        return body

    def merge_max(self, other: dict) -> None:
        """Fold a persisted counter dict in, taking the elementwise max —
        the idempotent merge for monotonic counters (a replayed older line
        can never roll a newer value back)."""
        for name in COUNTER_FIELDS:
            value = other.get(name)
            if isinstance(value, (int, float)):
                setattr(self, name, max(getattr(self, name), float(value)))
        for attr in ("outcomes", "violations"):
            table = other.get(attr)
            if isinstance(table, dict):
                mine = getattr(self, attr)
                for key, value in table.items():
                    if isinstance(value, (int, float)):
                        mine[str(key)] = max(
                            mine.get(str(key), 0.0), float(value)
                        )


@dataclass
class UsageDraft:
    """One request attempt's consumption, accumulated as the pipeline
    learns it and committed to the ledger in one call. A draft per ATTEMPT
    (the retry ladder creates a fresh one per try): a failed attempt
    consumed real device time and is billed; the logical request is
    counted once, at the API surface."""

    tenant: str
    chips: int = 1
    device_op_seconds: float = 0.0
    hbm_byte_seconds: float = 0.0
    upload_bytes: float = 0.0
    download_bytes: float = 0.0
    compile_cache_recompiles: float = 0.0
    compile_cache_new_bytes: float = 0.0
    batch_jobs: float = 0.0
    committed: bool = False

    @property
    def chip_seconds(self) -> float:
        return self.device_op_seconds * max(1, self.chips)


class UsageLedger:
    """The per-tenant accounting table plus its durability machinery.

    Event-loop-discipline like the scheduler: all mutation happens on the
    control plane's single loop; journal writes are small synchronous
    appends (one line per dirty tenant per flush)."""

    def __init__(
        self,
        config=None,
        *,
        metrics=None,
        walltime=time.time,
        replica_id: str | None = None,
    ) -> None:
        from ..config import Config
        from .state_store import resolve_replica_id

        self.config = config or Config()
        self.metrics = metrics
        self.walltime = walltime
        # Multi-writer sharding: in a replicated deployment every replica
        # journals to its OWN shard (journal-<replica>.jsonl /
        # snapshot-<replica>.json) — one writer per file, so concurrent
        # replicas on a shared volume can never tear or interleave each
        # other's lines (a multi-line flush exceeds PIPE_BUF, so two
        # appenders on ONE file WOULD interleave). Single-replica
        # deployments resolve to "" and keep the legacy file names
        # byte-for-byte; a replica also READS the legacy files at load so
        # turning replication on inherits the existing ledger.
        self.replica_id = (
            replica_id if replica_id is not None
            else resolve_replica_id(self.config)
        )
        # Exactly ONE replica inherits the legacy unsharded files (the
        # lexicographically-first configured peer — deterministic, no
        # coordination needed): if every replica folded the legacy totals
        # into its own shard, pre-migration history would be counted N
        # times across the fleet. A replicated posture WITHOUT a peer
        # list (shared store behind a plain load balancer) has nothing to
        # elect against, so NOBODY inherits — the legacy files stay on
        # disk untouched for the operator to fold in deliberately;
        # over-counting a fleet's bills silently is the worse failure.
        self._inherit_legacy = True
        if self.replica_id:
            from .replicas import parse_peers

            peers = sorted(
                parse_peers(getattr(self.config, "replica_peers", "") or "")
            )
            self._inherit_legacy = bool(peers) and self.replica_id == peers[0]
        self.enabled = bool(self.config.usage_metering_enabled)
        self.max_tenants = max(1, self.config.usage_max_tenants)
        self.flush_interval = max(0.1, self.config.usage_flush_interval)
        self.journal_max_bytes = max(4096, self.config.usage_journal_max_bytes)
        self.journal_keep_seconds = max(
            0.0, self.config.usage_journal_keep_seconds
        )
        self._tenants: dict[str, TenantUsage] = {}
        self._dirty: set[str] = set()
        self._task: asyncio.Task | None = None
        # The in-flight worker-thread write, if any: stop() must wait it
        # out before the final synchronous flush, or the thread's late
        # compaction could truncate the journal using a snapshot built
        # BEFORE the final flush's counters — erasing them from disk.
        self._write_future: asyncio.Future | None = None
        self._closed = False
        self.started_at = walltime()
        # Self-observability for /statusz.
        self.flushes = 0
        self.journal_lines = 0
        self.compactions = 0
        self.load_errors = 0
        if not self.enabled:
            # Kill switch: no directory, no load, no IO — the object exists
            # only so callers can hold a reference without None checks.
            self._dir = None
            return
        base = self.config.usage_journal_path or os.path.join(
            self.config.file_storage_path, ".usage"
        )
        self._dir = base
        os.makedirs(base, exist_ok=True)
        self._load()

    # --------------------------------------------------------------- recording

    def _resolve(self, tenant: str) -> tuple[str, TenantUsage]:
        """THE tenant-cap rule, in one place: the row `tenant`'s usage
        lands on and its name (which is also the metric label — ledger
        row and metric series can never diverge). A tenant with an
        existing row keeps it; a new tenant past the cap lands on
        `_overflow` — bounded table, but billing never drops
        consumption."""
        row = self._tenants.get(tenant)
        if row is not None:
            return tenant, row
        if (
            tenant != OVERFLOW_TENANT
            and len(self._tenants) >= self.max_tenants
        ):
            return self._resolve(OVERFLOW_TENANT)
        row = TenantUsage()
        self._tenants[tenant] = row
        return tenant, row

    def _restore_row(self, tenant: str) -> TenantUsage:
        """Load-path row accessor: persisted rows restore VERBATIM, never
        re-capped. The previous process already enforced its cap when it
        wrote them (the live table legitimately holds max_tenants real
        rows plus `_overflow`); rerouting the last one through `_resolve`'s
        cap on replay would max-merge a real tenant's bill into the
        overflow row — silently destroying it on every restart. A cap
        LOWERED between restarts keeps the old rows too (bills are never
        dropped); only NEW tenants feel the new bound."""
        row = self._tenants.get(tenant)
        if row is None:
            row = TenantUsage()
            self._tenants[tenant] = row
        return row

    def peek(self, tenant: str) -> tuple[str, TenantUsage | None]:
        """Non-mutating `_resolve`: the row label `tenant`'s usage WOULD
        land on (the same cap rule — a new tenant past the bound reads the
        `_overflow` row) and the current row, or None when the tenant has
        never been billed. The quota layer keys its window state by this
        label so enforcement and billing can never disagree about which
        row a tenant's consumption lives in — past the cap, minted tenant
        names all share `_overflow`'s budget, which makes name-minting a
        self-defeating evasion."""
        row = self._tenants.get(tenant)
        if row is not None:
            return tenant, row
        if (
            tenant != OVERFLOW_TENANT
            and len(self._tenants) >= self.max_tenants
        ):
            return OVERFLOW_TENANT, self._tenants.get(OVERFLOW_TENANT)
        return tenant, None

    def iter_persisted(self):
        """Yield ``(ts, tenant, counters, source)`` time-points from the
        snapshot (source="snapshot") and then the journal
        (source="journal"), in write order — the quota layer's window
        restore: each journal line is a timestamped CUMULATIVE counter
        sample, so replaying them rebuilds a sliding window's baseline to
        within one flush interval of where a SIGKILL'd process left it (an
        offender cannot earn a fresh budget by crashing the control
        plane). The source tag lets the consumer tell "this tenant's first
        persisted record ever" (journal line, no snapshot row — its
        pre-sample consumption is exactly zero) from "totals folded by a
        compaction" (snapshot row — pre-snapshot history is gone).
        Unreadable files and torn lines are skipped exactly like
        `_load`."""
        if self._dir is None:
            return
        for path in self._read_paths(self.snapshot_path, "snapshot.json"):
            try:
                with open(path, encoding="utf-8") as f:
                    body = json.load(f)
                ts = body.get("ts")
                tenants = body.get("tenants", {})
                if isinstance(ts, (int, float)) and isinstance(tenants, dict):
                    for tenant, counters in tenants.items():
                        if isinstance(counters, dict):
                            yield float(ts), str(tenant), counters, "snapshot"
            except (FileNotFoundError, json.JSONDecodeError, OSError):
                pass
        for path in self._read_paths(self.journal_path, "journal.jsonl"):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            entry = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        ts = entry.get("ts")
                        tenant = entry.get("tenant")
                        counters = entry.get("usage")
                        if (
                            isinstance(ts, (int, float))
                            and isinstance(tenant, str)
                            and isinstance(counters, dict)
                        ):
                            yield float(ts), tenant, counters, "journal"
            except (FileNotFoundError, OSError):
                pass

    def add(
        self,
        tenant: str,
        *,
        chip_seconds: float = 0.0,
        device_op_seconds: float = 0.0,
        queue_wait_seconds: float = 0.0,
        hbm_byte_seconds: float = 0.0,
        upload_bytes: float = 0.0,
        download_bytes: float = 0.0,
        compile_cache_recompiles: float = 0.0,
        compile_cache_new_bytes: float = 0.0,
        requests: float = 0.0,
        batch_jobs: float = 0.0,
        outcome: str | None = None,
        violation: str | None = None,
    ) -> None:
        """Fold one increment set into the tenant's counters (all values
        non-negative; negatives are clamped — monotonicity is the ledger's
        core contract)."""
        if not self.enabled:
            return
        label, row = self._resolve(tenant)
        increments = {
            "chip_seconds": chip_seconds,
            "device_op_seconds": device_op_seconds,
            "queue_wait_seconds": queue_wait_seconds,
            "hbm_byte_seconds": hbm_byte_seconds,
            "upload_bytes": upload_bytes,
            "download_bytes": download_bytes,
            "compile_cache_recompiles": compile_cache_recompiles,
            "compile_cache_new_bytes": compile_cache_new_bytes,
            "requests": requests,
            "batch_jobs": batch_jobs,
        }
        for name, amount in increments.items():
            if amount and amount > 0:
                setattr(row, name, getattr(row, name) + float(amount))
        if outcome:
            row.outcomes[outcome] = row.outcomes.get(outcome, 0.0) + 1.0
        if violation:
            row.violations[violation] = row.violations.get(violation, 0.0) + 1.0
        self._dirty.add(label)
        if self.metrics is not None:
            self.metrics.record_tenant_usage(
                label,
                increments,
                outcome=outcome,
                violation=violation,
            )

    def draft(self, tenant: str, chips: int = 1) -> UsageDraft | None:
        """A per-attempt accumulator, or None with the kill switch on (the
        pipeline's `if draft is not None` guards keep the disabled path
        byte-for-byte identical to pre-metering behavior)."""
        if not self.enabled:
            return None
        return UsageDraft(tenant=tenant, chips=max(1, chips))

    def commit(self, draft: UsageDraft | None) -> None:
        """Record one attempt's accumulated consumption (no request count —
        the API surface counts the logical request exactly once).
        Idempotent: a draft commits at most once, whatever path exits."""
        if draft is None or not self.enabled or draft.committed:
            return
        draft.committed = True
        if not (
            draft.device_op_seconds
            or draft.hbm_byte_seconds
            or draft.upload_bytes
            or draft.download_bytes
            or draft.compile_cache_recompiles
            or draft.compile_cache_new_bytes
            or draft.batch_jobs
        ):
            return
        self.add(
            draft.tenant,
            chip_seconds=draft.chip_seconds,
            device_op_seconds=draft.device_op_seconds,
            hbm_byte_seconds=draft.hbm_byte_seconds,
            upload_bytes=draft.upload_bytes,
            download_bytes=draft.download_bytes,
            compile_cache_recompiles=draft.compile_cache_recompiles,
            compile_cache_new_bytes=draft.compile_cache_new_bytes,
            batch_jobs=draft.batch_jobs,
        )

    # ---------------------------------------------------------------- surfaces

    def tenant_snapshot(self, tenant: str) -> dict | None:
        row = self._tenants.get(tenant)
        return row.as_dict() if row is not None else None

    def snapshot(self) -> dict:
        """The GET /usage body (and the /statusz usage section's source):
        every tenant row plus the ledger's own health."""
        return {
            "enabled": self.enabled,
            "since_unix": round(self.started_at, 3),
            "flush_interval_s": self.flush_interval,
            "tenants": {
                tenant: row.as_dict()
                for tenant, row in sorted(self._tenants.items())
            },
            "tenant_count": len(self._tenants),
            "max_tenants": self.max_tenants,
            "flushes": self.flushes,
            "journal_lines": self.journal_lines,
            "compactions": self.compactions,
        }

    # -------------------------------------------------------------- durability

    @property
    def journal_path(self) -> str | None:
        if self._dir is None:
            return None
        name = (
            f"journal-{self.replica_id}.jsonl"
            if self.replica_id
            else "journal.jsonl"
        )
        return os.path.join(self._dir, name)

    @property
    def snapshot_path(self) -> str | None:
        if self._dir is None:
            return None
        name = (
            f"snapshot-{self.replica_id}.json"
            if self.replica_id
            else "snapshot.json"
        )
        return os.path.join(self._dir, name)

    def _read_paths(self, own: str | None, legacy_name: str) -> list[str]:
        """Load-order file list: the legacy unsharded file first (only on
        the one DESIGNATED inheritor — see _inherit_legacy), then this
        replica's own shard. Peers' shards are deliberately NOT read —
        each replica's table is its own attribution (merging a peer's
        totals into this table would double-count them the moment both
        replicas flush)."""
        if own is None:
            return []
        paths = []
        if self.replica_id and self._inherit_legacy:
            legacy = os.path.join(self._dir, legacy_name)
            if legacy != own:
                paths.append(legacy)
        paths.append(own)
        return paths

    def _load(self) -> None:
        """Rebuild the table: snapshot first, then journal lines on top.
        Cumulative latest-wins lines + elementwise-max merge make the
        replay exact no matter where the previous process died."""
        for path in self._read_paths(self.snapshot_path, "snapshot.json"):
            try:
                with open(path, encoding="utf-8") as f:
                    body = json.load(f)
                tenants = body.get("tenants", {})
                if isinstance(tenants, dict):
                    for tenant, counters in tenants.items():
                        if isinstance(counters, dict):
                            self._restore_row(str(tenant)).merge_max(counters)
            except FileNotFoundError:
                pass
            except (json.JSONDecodeError, OSError):
                self.load_errors += 1
                logger.warning(
                    "usage snapshot unreadable; continuing from the journal",
                    exc_info=True,
                )
        for path in self._read_paths(self.journal_path, "journal.jsonl"):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            entry = json.loads(line)
                        except json.JSONDecodeError:
                            # A torn tail line (SIGKILL mid-write):
                            # everything before it already replayed; at
                            # most one flush interval of attribution is
                            # gone — the documented durability bound.
                            self.load_errors += 1
                            logger.warning(
                                "skipping torn usage-journal line (%d bytes)",
                                len(line),
                            )
                            continue
                        tenant = entry.get("tenant")
                        counters = entry.get("usage")
                        if isinstance(tenant, str) and isinstance(counters, dict):
                            self._restore_row(tenant).merge_max(counters)
            except FileNotFoundError:
                pass
            except OSError:
                self.load_errors += 1
                logger.warning("usage journal unreadable", exc_info=True)
        if self._tenants:
            logger.info(
                "usage ledger restored %d tenant row(s) from %s",
                len(self._tenants),
                self._dir,
            )

    def _prepare_flush(self) -> dict | None:
        """ON-LOOP half of a flush: drain the dirty set and serialize the
        rows while no other code can mutate them (single event loop), so
        the IO half can run on a worker thread without racing `add()`.
        The full-table snapshot rides along in case the write side decides
        to compact. Returns None when there is nothing to write."""
        if not self.enabled or not self._dirty:
            return None
        dirty = sorted(self._dirty)
        self._dirty.clear()
        now = self.walltime()
        lines = [
            json.dumps(
                {
                    "tenant": tenant,
                    "usage": self._tenants[tenant].as_dict(),
                    "ts": round(now, 3),
                },
                sort_keys=True,
            )
            for tenant in dirty
            if tenant in self._tenants
        ]
        if not lines:
            return None
        snapshot_body = {
            "version": 1,
            "ts": round(now, 3),
            "tenants": {
                tenant: row.as_dict() for tenant, row in self._tenants.items()
            },
        }
        return {"dirty": dirty, "lines": lines, "snapshot": snapshot_body}

    def _write_flush(self, payload: dict) -> int:
        """IO half of a flush (thread-safe: touches only files and
        GIL-atomic counters/sets). Append failure re-marks the tenants
        dirty — their lines never reached disk, so the next cycle retries.
        Compaction failure does NOT: the appended lines are already
        durable, and re-marking them would re-append identical lines every
        interval while (say) ENOSPC keeps the snapshot write failing —
        growing the journal without bound exactly when disk is short."""
        dirty, lines = payload["dirty"], payload["lines"]
        try:
            with open(self.journal_path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            self._dirty.update(dirty)
            logger.warning("usage journal flush failed", exc_info=True)
            return 0
        self.journal_lines += len(lines)
        self.flushes += 1
        try:
            if os.path.getsize(self.journal_path) > self.journal_max_bytes:
                self._compact(payload["snapshot"])
        except OSError:
            logger.warning(
                "usage journal compaction failed; journal keeps growing "
                "until a later compaction succeeds (replay stays exact)",
                exc_info=True,
            )
        return len(lines)

    def flush(self) -> int:
        """Append one cumulative line per dirty tenant; compact when the
        journal outgrows its bound. Returns lines written. Never raises —
        a full disk degrades durability, not serving. Synchronous (tests,
        close()); the flush daemon uses `flush_off_loop` so fsync latency
        never stalls the serving event loop."""
        payload = self._prepare_flush()
        if payload is None:
            return 0
        return self._write_flush(payload)

    async def flush_off_loop(self) -> int:
        """The daemon's flush: rows serialize on-loop (no concurrent
        mutation), the write+fsync (up to 100ms+ on a throttled disk)
        runs on a worker thread — in-flight requests never pay for
        telemetry durability. The thread future is tracked so stop() can
        wait it out: cancelling a task awaiting to_thread returns
        immediately while the THREAD keeps running."""
        payload = self._prepare_flush()
        if payload is None:
            return 0
        future = asyncio.ensure_future(
            asyncio.to_thread(self._write_flush, payload)
        )
        self._write_future = future
        try:
            return await asyncio.shield(future)
        finally:
            if future.done():
                self._write_future = None

    def _recent_journal_tail(self) -> list[str]:
        """The journal lines compaction RETAINS: newer than
        journal_keep_seconds, bounded to half the journal size cap (oldest
        dropped first). These are stale cumulative values the max-merge
        replays as no-ops — kept purely as the TIMELINE the quota layer's
        sliding windows restore from after a crash. Unparseable lines are
        dropped (the snapshot already holds their totals)."""
        if self.journal_keep_seconds <= 0:
            return []
        cutoff = self.walltime() - self.journal_keep_seconds
        kept: list[str] = []
        kept_bytes = 0
        try:
            with open(self.journal_path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ts = json.loads(line).get("ts")
                    except json.JSONDecodeError:
                        continue
                    if isinstance(ts, (int, float)) and ts >= cutoff:
                        kept.append(line)
                        kept_bytes += len(line) + 1
        except OSError:
            return []
        bound = self.journal_max_bytes // 2
        while kept and kept_bytes > bound:
            kept_bytes -= len(kept.pop(0)) + 1
        return kept

    def _compact(self, snapshot_body: dict) -> None:
        """Fold the passed table snapshot into the snapshot file (atomic
        tmp+rename) and rewrite the journal down to its recent tail (the
        timeline quota windows restore from; empty with retention off). A
        crash between the two replays the stale journal over the fresh
        snapshot — idempotent by the max-merge. The tmp file is removed on
        failure so a dead partial write can't linger."""
        tail = self._recent_journal_tail()
        tmp = self.snapshot_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snapshot_body, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # The journal rewrite is atomic too (tmp+rename): a SIGKILL landing
        # mid-compaction must leave either the OLD journal (stale lines the
        # max-merge replays as no-ops, timeline intact) or the NEW tail —
        # never a truncated-but-unwritten journal, which would erase the
        # window timeline the quota layer restores from exactly when the
        # crash-resistance property is being exercised.
        jtmp = self.journal_path + ".tmp"
        try:
            with open(jtmp, "w", encoding="utf-8") as f:
                if tail:
                    f.write("\n".join(tail) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(jtmp, self.journal_path)
        except OSError:
            try:
                os.unlink(jtmp)
            except OSError:
                pass
            raise
        self.compactions += 1

    # -------------------------------------------------------------- flush loop

    def start(self) -> asyncio.Task | None:
        """Run periodic flushes until stop()/close() — the device_health-
        style daemon half; __main__ owns the lifecycle. Disabled ledgers
        return None (no task, no IO)."""
        if not self.enabled or self._task is not None:
            return self._task

        async def loop() -> None:
            while not self._closed:
                await asyncio.sleep(self.flush_interval)
                try:
                    await self.flush_off_loop()
                except Exception:  # noqa: BLE001 — metering must never die
                    logger.exception("usage flush cycle failed")

        self._task = asyncio.get_running_loop().create_task(loop())
        return self._task

    async def stop(self) -> None:
        """Stop the flush loop and ship a final flush (restart-safe).
        An in-flight worker-thread write is AWAITED first: the final
        flush must strictly follow it, or the thread's late compaction
        would truncate the journal with a pre-final-flush snapshot and
        erase the drain window's attribution."""
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        write = self._write_future
        if write is not None and not write.done():
            await asyncio.gather(write, return_exceptions=True)
        self._write_future = None
        self._closed = False
        self.flush()

    def close(self) -> None:
        """Synchronous final flush (the executor's close path — by then the
        loop task is already stopped or was never started)."""
        self._closed = True
        try:
            self.flush()
        except Exception:  # noqa: BLE001
            logger.exception("final usage flush failed")
