"""Device-health probe daemon: the detection half of wedge recovery.

The repo's own bench history (BENCH_r03–r05) records the production failure
mode this module exists for: a TPU attach blocking 50–76 minutes after a
mid-device-op SIGKILL, with ``/healthz`` answering "ok" the whole time —
nothing distinguished *busy* from *wedged*, and the recovery story was an
operator ssh-ing into a watcher script (``scripts/onchip_watch.sh``). The
ROADMAP's fencing item needs observation before it can get actuation; this
daemon is that observation layer. A ``wedged`` verdict marks the host
(``sandbox.meta["device_health"]``), fires ``device_wedge_detected_total``,
records a transition trace — and now ACTS: the verdict is handed to the
executor's fencing actuator (``CodeExecutor.on_host_wedged`` — lease
revocation, lane drain, dispose-and-replace; every safety bound lives
there), and hosts on a fenced scope ride the ``recovering`` →
re-admission state machine here (``_recovery_overlay``): probed but
serving nothing until ``APP_DEVICE_PROBE_READMIT_STREAK`` consecutive
clean cycles, with suspect relapse resetting the streak.

Mechanics: every ``APP_DEVICE_PROBE_INTERVAL`` seconds, one cycle samples
``GET /device-stats`` on every live sandbox host (the executor's registry —
pooled, in-use, and session-parked sandboxes alike) and classifies each
host into a typed state:

- ``healthy`` — reachable, no device op in flight, nothing stalled.
- ``busy``    — an attach or device op is running inside its budget.
- ``suspect`` — something is past its budget (attach older than
  ``APP_DEVICE_PROBE_ATTACH_BUDGET``, an op older than its own declared
  timeout plus ``APP_DEVICE_PROBE_OP_GRACE``, or the host stopped answering
  probes) but not yet long enough to call dead.
- ``wedged``  — the stall has persisted ``APP_DEVICE_PROBE_WEDGE_AFTER``
  seconds past the budget: the device plane stopped making progress and no
  in-band mechanism is going to unstick it.

Ages come from the executor server's own monotonic clock (``/device-stats``
reports ages, not timestamps), so no cross-host clock math happens here.
Transitions touching suspect/wedged — entering trouble or recovering from
it; routine healthy<->busy flips stay silent — emit a
``device_health.transition`` span into the trace ring (recorded at ANY
sampling ratio — such transitions are rare and exactly what an operator
pulls up after an incident; only the tracing kill switch drops them, along
with the whole /traces surface) and the state surface feeds ``/statusz``,
the
``device_health_state`` gauge (host labels capped —
``APP_DEVICE_PROBE_MAX_HOST_LABELS`` — past which series aggregate per
lane), and the OTLP metrics export.

The probe daemon is itself observable: ``device_probe_last_poll_age_seconds``
and ``code_interpreter_device_probe_cycle_seconds`` expose a stalled or
slow probe loop (a wedge nobody is probing for is invisible).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

import httpx

from ..utils import tracing

logger = logging.getLogger(__name__)

HEALTHY = "healthy"
BUSY = "busy"
# The two actuation states the fencing layer added on top of PR 8's four
# classifications. RECOVERING: the host sits on a fenced lease scope and
# probes clean, but has not yet shown the configured consecutive-clean
# streak — it is probed and counted, never handed a request. DRAINING: the
# actuator fenced this host (lease revoked, drain + dispose in flight);
# the state is terminal — the host leaves the table when disposal lands.
RECOVERING = "recovering"
SUSPECT = "suspect"
WEDGED = "wedged"
DRAINING = "draining"
STATES = (HEALTHY, BUSY, RECOVERING, SUSPECT, WEDGED, DRAINING)

# Severity order for "did this transition get worse?" decisions.
_SEVERITY = {state: i for i, state in enumerate(STATES)}


@dataclass
class HostHealth:
    """One probed host's current classification and supporting evidence."""

    lane: int
    sandbox_id: str
    host: str
    state: str = HEALTHY
    since: float = 0.0  # probe clock: when `state` was entered
    reason: str = ""  # which signal produced the state
    stall_s: float = 0.0  # seconds past budget (suspect/wedged evidence)
    failures: int = 0  # consecutive probe failures
    last_success: float | None = None  # probe clock
    first_failure: float | None = None
    legacy: bool = False  # old executor binary: no /device-stats route
    stats: dict = field(default_factory=dict)  # last good /device-stats body

    def snapshot(self) -> dict:
        """The /statusz row for this host."""
        row = {
            "lane": self.lane,
            "sandbox": self.sandbox_id,
            "host": self.host,
            "state": self.state,
            "reason": self.reason,
            "stall_s": round(self.stall_s, 3),
            "probe_failures": self.failures,
        }
        if self.legacy:
            row["legacy"] = True
        stats = self.stats
        if stats:
            row["device_count"] = stats.get("device_count")
            row["device_kind"] = stats.get("device_kind") or stats.get(
                "backend"
            )
            row["warm_state"] = stats.get("warm_state")
            row["op_in_flight"] = bool(stats.get("op_in_flight"))
            row["attach_seconds"] = stats.get("attach_seconds")
            row["rss_bytes"] = stats.get("rss_bytes")
            row["runner_rss_bytes"] = stats.get("runner_rss_bytes")
            row["last_device_op_age_s"] = stats.get("last_device_op_age_s")
        return row


class DeviceHealthProbe:
    """Samples every live sandbox host and keeps the typed state machine.

    ``executor`` supplies the host inventory (``live_hosts()``) and the
    HTTP client (which carries the chaos backend's fault transport — the
    attach-hang injection reaches the probe exactly the way a real wedged
    host would). ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        executor,
        *,
        config=None,
        metrics=None,
        tracer=None,
        clock=time.monotonic,
        walltime=time.time,
    ) -> None:
        self.executor = executor
        self.config = config or executor.config
        self.metrics = metrics or executor.metrics
        self.tracer = tracer or executor.tracer
        self.clock = clock
        self.walltime = walltime
        self.interval = max(0.0, self.config.device_probe_interval)
        self.timeout = max(0.1, self.config.device_probe_timeout)
        self.attach_budget = max(0.0, self.config.device_probe_attach_budget)
        self.op_grace = max(0.0, self.config.device_probe_op_grace)
        self.wedge_after = max(0.0, self.config.device_probe_wedge_after)
        self.max_host_labels = max(1, self.config.device_probe_max_host_labels)
        self._hosts: dict[str, HostHealth] = {}
        # Per-cycle recovery verdicts: lease scope -> [all_clean, lane].
        # Aggregated across the scope's hosts and settled ONCE per cycle
        # (note_probe per host would let a two-host scope double-count its
        # clean streak).
        self._scope_clean: dict[str, list] = {}
        self._task: asyncio.Task | None = None
        self._closed = False
        self._last_cycle_end: float | None = None
        self._cycles = 0
        self.metrics.bind_device_health(self)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> asyncio.Task | None:
        """Run probe cycles on the configured cadence until stop().
        interval == 0 disables the daemon (returns None, no task)."""
        if self.interval <= 0 or self._task is not None:
            return self._task

        async def loop() -> None:
            # Probe work must never attach spans/events to whatever request
            # context was current when start() ran.
            tracing.current_span_var.set(None)
            # Probe first, then sleep: the daemon's first verdicts exist
            # one cycle after start, not one interval later — a wedge
            # present at boot is visible immediately.
            while not self._closed:
                try:
                    await self.probe_once()
                except Exception:  # noqa: BLE001 — keep probing
                    logger.exception("device-health probe cycle failed")
                await asyncio.sleep(self.interval)

        self._task = asyncio.get_running_loop().create_task(loop())
        return self._task

    async def stop(self) -> None:
        """Stop the probe loop. Restart-safe: a later start() begins a
        fresh loop (the overhead bench toggles the daemon A/B on one live
        stack)."""
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._closed = False

    # ----------------------------------------------------------- probe cycle

    async def probe_once(self) -> dict[str, str]:
        """One full cycle: sample every live host, classify, prune hosts
        that no longer exist. Returns {host_url: state} for tests."""
        start = self.clock()
        targets: list[tuple[int, object, str]] = []
        seen: set[str] = set()
        for lane, sandbox in self.executor.live_hosts():
            for url in sandbox.host_urls:
                if url in seen:
                    continue  # one sandbox can be re-pooled, not re-probed
                seen.add(url)
                targets.append((lane, sandbox, url))
        self._scope_clean = {}
        await asyncio.gather(
            *(self._probe_host(lane, sandbox, url) for lane, sandbox, url in targets)
        )
        # A disposed sandbox's host must leave the table (and the gauge) —
        # a wedged verdict on a host that no longer exists is stale noise.
        for url in list(self._hosts):
            if url not in seen:
                del self._hosts[url]
        # Settle recovery streaks AFTER the full cycle: one note per scope
        # per cycle, clean only when every host on the scope probed clean.
        self._settle_recovery()
        elapsed = max(0.0, self.clock() - start)
        self._last_cycle_end = self.clock()
        self._cycles += 1
        self.metrics.device_probe_cycle_seconds.observe(elapsed)
        return {url: h.state for url, h in self._hosts.items()}

    async def _probe_host(self, lane: int, sandbox, url: str) -> None:
        health = self._hosts.get(url)
        if health is None:
            health = HostHealth(
                lane=lane,
                sandbox_id=getattr(sandbox, "id", ""),
                host=url,
                since=self.clock(),
            )
            self._hosts[url] = health
        else:
            # The same URL can be a recycled sandbox in a new role.
            health.lane = lane
            health.sandbox_id = getattr(sandbox, "id", health.sandbox_id)
        stats: dict | None = None
        legacy = False
        try:
            resp = await self.executor._http_client().get(
                f"{url}/device-stats", timeout=self.timeout
            )
            if resp.status_code == 404:
                legacy = True  # old binary: no stats route, but it answered
            elif resp.status_code == 200:
                body = resp.json()
                if isinstance(body, dict):
                    stats = body
        except (httpx.HTTPError, ValueError):
            stats = None
        now = self.clock()
        if stats is None and not legacy:
            health.failures += 1
            if health.first_failure is None:
                health.first_failure = now
            state, reason, stall = self._classify_unreachable(health, now)
        else:
            health.failures = 0
            health.first_failure = None
            health.last_success = now
            health.legacy = legacy
            if legacy:
                # Can't see the device plane on an old binary; reachable is
                # all the evidence there is.
                state, reason, stall = HEALTHY, "legacy_binary", 0.0
            else:
                health.stats = stats
                state, reason, stall = self._classify(stats)
        state, reason = self._recovery_overlay(health, state, reason)
        self._apply(health, state, reason, stall, now)

    # ---------------------------------------------------- recovery actuation

    def _lease_state(self, health: HostHealth):
        """(registry, lease) for the host's sandbox, or (None, None) when
        fencing is not wired (no registry) or the sandbox is already gone."""
        registry = getattr(self.executor, "leases", None)
        entry = self.executor.live_sandbox(health.sandbox_id)
        if registry is None or entry is None:
            return None, None
        return registry, entry[1].meta.get("lease")

    def _recovery_overlay(
        self, health: HostHealth, state: str, reason: str
    ) -> tuple[str, str]:
        """Layer the fencing/recovery state machine over the raw
        classification. A fenced host reads DRAINING until its disposal
        prunes it from the table; a host on a recovering scope reads
        RECOVERING while it earns the clean-probe streak (its per-cycle
        verdict is banked for `_settle_recovery`), and a suspect/wedged
        relapse banks a reset instead."""
        registry, lease = self._lease_state(health)
        entry = self.executor.live_sandbox(health.sandbox_id)
        if entry is not None and entry[1].meta.get("lease_fenced"):
            return DRAINING, "fenced"
        if registry is None or lease is None or not registry.recovering(
            lease.scope
        ):
            return state, reason
        verdict = self._scope_clean.setdefault(
            lease.scope, [True, health.lane]
        )
        if state in (HEALTHY, BUSY):
            streak, need = registry.recovery_progress(lease.scope)
            return (
                RECOVERING,
                f"clean_streak_{min(streak + 1, need)}_of_{need}",
            )
        # Relapse (suspect/unreachable/wedged mid-streak): the streak
        # resets at settle time — and the host STAYS quarantined. A
        # suspect relapse must keep reading RECOVERING: the raw suspect
        # state is not in the pool's unservable set, so passing it through
        # would flip the host from standby to servable supply and hand a
        # tenant request to hardware that just showed stall symptoms —
        # exactly what the re-admission gate exists to prevent. Only a
        # WEDGED relapse passes through raw: it must re-trigger actuation
        # (budget-bounded), and wedged is unservable in its own right.
        verdict[0] = False
        if state == WEDGED:
            return state, reason
        return RECOVERING, f"relapse_{reason}" if reason else "relapse"

    def _settle_recovery(self) -> None:
        """Apply the cycle's per-scope verdicts to the lease registry and
        act on re-admissions: the scope's hosts flip to healthy NOW (the
        pool's supply gating reads the sandbox marks, and a woken waiter
        must see serving supply, not last cycle's quarantine), the
        re-admission counter fires, and every lane is kicked — waiters
        parked behind the recovering quarantine are exactly who this
        turnover is for."""
        registry = getattr(self.executor, "leases", None)
        if registry is None:
            return
        for scope, (clean, lane) in self._scope_clean.items():
            if not registry.note_probe(scope, clean=clean):
                continue
            self.metrics.host_readmitted.inc(lane=str(lane))
            for health in self._hosts.values():
                if health.state != RECOVERING:
                    continue
                _, lease = self._lease_state(health)
                if lease is None or lease.scope != scope:
                    continue
                health.state = HEALTHY
                health.reason = "readmitted"
                health.since = self.clock()
                self._mark_sandbox(health)
            self.tracer.record_span(
                "device_health.readmitted",
                trace_id=tracing.new_trace_id(),
                parent_id=None,
                start_unix=self.walltime(),
                duration_s=0.0,
                attributes={"lane": lane, "scope": scope},
            )
            kick = getattr(self.executor, "_notify_all_lanes", None)
            if kick is not None:
                kick()
        self._scope_clean = {}

    # -------------------------------------------------------- classification

    def _classify(self, stats: dict) -> tuple[str, str, float]:
        """Map one /device-stats body to (state, reason, stall seconds).
        `stall` is how far past its budget the slowest signal is — suspect
        at 0, wedged once it persists `wedge_after`."""

        def age(key: str) -> float:
            value = stats.get(key)
            return float(value) if isinstance(value, (int, float)) else 0.0

        # Attach (warm-up: jax import + libtpu init + device enumeration)
        # in flight: legitimate for minutes, wedged when it outlives the
        # budget — THE historical failure signature (BENCH_r03-r05).
        # warm_state "pending" alone counts too: an attach observed at age
        # zero is still an attach.
        attach_pending = age("attach_pending_s")
        if attach_pending > 0 or stats.get("warm_state") == "pending":
            stall = attach_pending - self.attach_budget
            if stall >= self.wedge_after:
                return WEDGED, "attach_stalled", stall
            if stall >= 0:
                return SUSPECT, "attach_over_budget", stall
            return BUSY, "attaching", 0.0
        # Device op in flight: budget is the op's OWN declared timeout plus
        # grace for the executor's kill/collect machinery. An op past that
        # means the timeout kill itself is stuck — the wedge, not the work.
        if stats.get("op_in_flight"):
            op_age = age("op_age_s")
            budget = age("op_timeout_s") + self.op_grace
            stall = op_age - budget
            if stall >= self.wedge_after:
                return WEDGED, "device_op_stalled", stall
            if stall >= 0:
                return SUSPECT, "device_op_over_budget", stall
            return BUSY, "device_op", 0.0
        if stats.get("warm_state") == "failed":
            # Warm-up failed: the host serves cold (or is about to be
            # disposed) — not wedged, but not healthy either.
            return SUSPECT, "warm_failed", 0.0
        if stats.get("warm_state") == "ready" and stats.get("runner_alive") is False:
            # The warm runner died SILENTLY while idle (OOM kill between
            # requests — the executor's waitid peek exposes the corpse):
            # the host would serve its next request cold and lose any
            # session state. Suspect, not wedged: the executor restarts
            # the runner in the background at next use.
            return SUSPECT, "runner_dead", 0.0
        # NOTE: runner_heartbeat_age_s is deliberately NOT thresholded
        # while the host is idle — an idle runner legitimately says
        # nothing for hours. Its age is meaningful evidence only inside
        # an attach or op window, where the attach/op stall rules above
        # already bound the same silence.
        return HEALTHY, "", 0.0

    def _classify_unreachable(
        self, health: HostHealth, now: float
    ) -> tuple[str, str, float]:
        """A host that stopped answering the stats probe entirely: suspect
        immediately, wedged once it has been dark past the wedge threshold.
        The baseline is the last successful probe (or the first failure for
        a host that never answered)."""
        base = (
            health.last_success
            if health.last_success is not None
            else health.first_failure
        )
        stall = max(0.0, now - (base if base is not None else now))
        if stall >= self.wedge_after:
            return WEDGED, "unreachable", stall
        return SUSPECT, "unreachable", stall

    # ------------------------------------------------------------ transition

    def _apply(
        self, health: HostHealth, state: str, reason: str, stall: float, now: float
    ) -> None:
        health.reason = reason
        health.stall_s = max(0.0, stall)
        previous = health.state
        if state == previous:
            self._mark_sandbox(health)
            if state == WEDGED:
                # Re-assert the verdict every cycle it stands: a fence the
                # actuator DEFERRED (budget exhausted, breaker open) gets
                # retried once the window slides, without needing a fresh
                # transition.
                self._actuate_wedge(health)
            return
        health.state = state
        health.since = now
        self._mark_sandbox(health)
        if state == WEDGED:
            self._actuate_wedge(health)
        # healthy<->busy flips are NORMAL OPERATION (every probe cycle that
        # catches a host mid-op produces one): they update state silently.
        # Only transitions touching recovering/suspect/wedged/draining —
        # entering trouble, recovering from it, or being fenced — are
        # incidents worth a log line and a span; anything louder floods the
        # log and evicts real request traces from the ring under ordinary
        # load.
        interesting = (
            _SEVERITY[state] >= _SEVERITY[RECOVERING]
            or _SEVERITY[previous] >= _SEVERITY[RECOVERING]
        )
        if not interesting:
            logger.debug(
                "device health: %s (lane=%d) %s -> %s",
                health.host,
                health.lane,
                previous,
                state,
            )
            return
        logger.log(
            logging.WARNING if _SEVERITY[state] > _SEVERITY[previous] else logging.INFO,
            "device health: %s (lane=%d, sandbox=%s) %s -> %s (%s, stall=%.1fs)",
            health.host,
            health.lane,
            health.sandbox_id,
            previous,
            state,
            reason or "recovered",
            health.stall_s,
        )
        # Suspect/wedged transitions are rare and exactly what an incident
        # review pulls up: record_span bypasses head sampling (a fresh
        # trace id, zero-duration span), so they are retrievable via
        # /traces at ANY sample ratio. Only the tracing kill switch
        # (APP_TRACING_ENABLED=0) drops them — it disables the whole
        # /traces surface, and the wedge stays visible through the
        # counter, /statusz, and the log line above.
        self.tracer.record_span(
            "device_health.transition",
            trace_id=tracing.new_trace_id(),
            parent_id=None,
            start_unix=self.walltime(),
            duration_s=0.0,
            attributes={
                "lane": health.lane,
                "host": health.host,
                "sandbox": health.sandbox_id,
                "from": previous,
                "to": state,
                "reason": reason,
                "stall_s": round(health.stall_s, 3),
            },
            status="error" if state == WEDGED else "ok",
        )
        if state == WEDGED:
            self.metrics.device_wedges.inc(chip_count=str(health.lane))

    def _actuate_wedge(self, health: HostHealth) -> None:
        """Hand the wedged verdict to the executor's fencing actuator —
        detect→act is one hop now. The actuator owns every safety bound
        (kill switch, per-lane budget, breaker state, dedupe), so calling
        it is always safe; absent actuator = detection-only (PR 8)."""
        actuate = getattr(self.executor, "on_host_wedged", None)
        if actuate is not None:
            actuate(health.sandbox_id, reason=health.reason or "wedged")

    def _mark_sandbox(self, health: HostHealth) -> None:
        """Stamp the verdict onto the sandbox itself — the handle the
        fencing layer (and /statusz consumers holding a Sandbox) will read.
        Detection only: nothing here disposes or drains."""
        entry = self.executor.live_sandbox(health.sandbox_id)
        if entry is not None:
            entry[1].meta["device_health"] = health.state

    # -------------------------------------------------------------- surfaces

    def last_poll_age(self) -> float:
        """Seconds since the last completed cycle (-1 = never completed) —
        the probe daemon's own liveness gauge."""
        if self._last_cycle_end is None:
            return -1.0
        return max(0.0, self.clock() - self._last_cycle_end)

    def gauge_samples(self) -> dict[tuple[str, ...], float]:
        """device_health_state{lane,host,state} feed, scrape-time. Under the
        host-label cap: one-hot per host. Past it: every series collapses
        to lane level (host="_overflow", value = hosts of that lane in that
        state) — the same cardinality discipline as the scheduler's tenant
        cap, applied to hosts."""
        hosts = list(self._hosts.values())
        overflow = len(hosts) > self.max_host_labels
        samples: dict[tuple[str, ...], float] = {}
        for health in hosts:
            host_label = "_overflow" if overflow else health.host
            if overflow:
                key = (str(health.lane), host_label, health.state)
                samples[key] = samples.get(key, 0.0) + 1.0
            else:
                for state in STATES:
                    key = (str(health.lane), host_label, state)
                    samples[key] = 1.0 if state == health.state else 0.0
        return samples

    def states(self) -> dict[str, str]:
        return {url: h.state for url, h in self._hosts.items()}

    def lane_census(self) -> dict[int, dict[str, int]]:
        """Per-lane state counts for the /healthz lane rows (satellite: an
        operator watching /healthz should see a lane's wedged/recovering
        hosts next to its queue and supply numbers, without a /statusz
        round-trip). Only states with a nonzero count appear — a healthy
        fleet's rows stay as small as before."""
        census: dict[int, dict[str, int]] = {}
        for health in self._hosts.values():
            lane = census.setdefault(health.lane, {})
            lane[health.state] = lane.get(health.state, 0) + 1
        return census

    def snapshot(self) -> dict:
        """The /statusz device-health block: per-host rows plus a state
        census and the probe's own liveness."""
        hosts = [h.snapshot() for h in self._hosts.values()]
        hosts.sort(key=lambda row: (row["lane"], row["host"]))
        census: dict[str, int] = {state: 0 for state in STATES}
        for health in self._hosts.values():
            census[health.state] = census.get(health.state, 0) + 1
        return {
            "enabled": self.interval > 0,
            "interval_s": self.interval,
            "thresholds": {
                "attach_budget_s": self.attach_budget,
                "op_grace_s": self.op_grace,
                "wedge_after_s": self.wedge_after,
            },
            "cycles": self._cycles,
            "last_poll_age_s": round(self.last_poll_age(), 3),
            "states": census,
            "hosts": hosts,
        }
