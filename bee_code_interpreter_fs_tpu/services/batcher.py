"""Batched multi-chip execution lanes: the coalescing window.

Before this subsystem, 8 queued one-chip-sized jobs on an 8-chip lane ran
as 8 serial sandbox round-trips — 7/8 of the slice idle at every instant.
"Podracer architectures for scalable RL" (PAPERS.md) shows where multi-chip
throughput actually comes from: the Anakin/Sebulba pattern keeps every chip
in a slice busy on *batched small work* dispatched as one program; and the
Kubernetes GenAI-inference evaluation finds request-coalescing (not pod
count) is what moves aggregate throughput for bursty inference-shaped
traffic. This module is the layer between the admission-control scheduler
and the executor that does the coalescing.

Design:

- **Compatibility keying** — jobs may share a dispatch only when they share
  a :class:`BatchKey`: lane (chip count), tenant, priority class, the exact
  env map, and the exact effective resource budget. Tenant is in the key by
  construction, so batching NEVER crosses tenants — two tenants' code never
  shares a sandbox generation through this path (the trust property the
  whole sandbox model rests on).
- **Bounded window** — the first job of a key arms a timer
  (``APP_BATCH_WINDOW_MS``); partners joining before it fires ride along;
  a full batch (``APP_BATCH_MAX_JOBS``) dispatches immediately. The window
  is the ONLY latency batching ever adds, and only to the first job.
- **Demux contract** — the dispatch callback resolves each job's future
  individually (per-job Result, violation, or error). Any batch-level
  fault falls back to the serial path per job, so no request ever fails
  *because* it was batched (`code_executor._dispatch_batch` owns that
  fallback; this module owns the grouping and the promise lifecycle).

The timer is injectable (``timer``) so the window-expiry tests run on a
fake clock with zero sleeps, like the scheduler's.
"""

from __future__ import annotations

import asyncio
import logging
from collections.abc import Awaitable, Callable
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


def freeze_mapping(mapping: dict | None) -> tuple:
    """A dict as a hashable, order-insensitive key component."""
    if not mapping:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in mapping.items()))


@dataclass(frozen=True)
class BatchKey:
    """What must match for two jobs to share one dispatch. Everything here
    is either placement (lane), isolation (tenant), scheduling class
    (priority), or process-global state inside the fused run (env, limits,
    timeout — one address space arms ONE rlimit set and ONE environ, and
    the fused run has ONE deadline, so only jobs with the SAME timeout may
    share it: a 5s job must never ride a 300s batch window)."""

    lane: int
    tenant: str
    priority: str
    env: tuple = ()
    limits: tuple = ()
    timeout: float = 0.0


@dataclass
class BatchJob:
    """One coalesced request: its source, its own timeout, and the promise
    the submitting request awaits. Trace identity rides along so the
    dispatcher can graft per-job sandbox timings back into the ORIGINATING
    request's trace (the demux half of observability)."""

    source_code: str
    timeout: float
    future: asyncio.Future = field(
        default_factory=lambda: asyncio.get_running_loop().create_future()
    )
    trace_id: str | None = None
    parent_span_id: str | None = None
    # perf_counter() at submission: lets the dispatcher report each job's
    # REAL queue wait (batching window + scheduler wait) in its Result
    # phases — the serial path reports queue_wait, so the fused path must
    # too, or batched requests look instantaneous on latency dashboards.
    submitted_at: float = 0.0
    # The submitter declared purity (result-memo miss in flight): the
    # dispatcher forwards the declaration per job so the executor echoes a
    # hashed result block, and the serial fallback re-asserts it in each
    # job's own task context. Carried on the job because the batcher's
    # dispatch task does NOT inherit the submitter's contextvars.
    pure: bool = False

    def resolve(self, result) -> None:
        if not self.future.done():
            self.future.set_result(result)

    def fail(self, error: BaseException) -> None:
        if not self.future.done():
            self.future.set_exception(error)


class Batcher:
    """The coalescing window between admission and dispatch.

    ``dispatch`` is an async callable ``(key, jobs) -> None`` that MUST
    settle every job's future (the executor's `_dispatch_batch`). It runs
    in a tracked background task — the submitting requests are all parked
    on their futures, so nobody's context is "the" dispatch context.
    """

    def __init__(
        self,
        *,
        window_s: float,
        max_jobs: int,
        dispatch: Callable[[BatchKey, list[BatchJob]], Awaitable[None]],
        timer: Callable[[float, Callable[[], None]], object] | None = None,
    ) -> None:
        self.window_s = max(0.0, window_s)
        self.max_jobs = max(1, max_jobs)
        self._dispatch = dispatch
        self._timer = timer or self._default_timer
        self._pending: dict[BatchKey, list[BatchJob]] = {}
        self._timers: dict[BatchKey, object] = {}
        self._tasks: set[asyncio.Task] = set()
        self._closed = False
        # Dispatch stats (read by tests and the healthz detail).
        self.dispatched_batches = 0
        self.dispatched_jobs = 0

    @staticmethod
    def _default_timer(delay: float, callback: Callable[[], None]):
        """Real deployments use the loop's timer; tests inject a manual one
        (capture the callback, fire it from a fake clock)."""
        return asyncio.get_running_loop().call_later(delay, callback)

    def pending_jobs(self, key: BatchKey) -> int:
        return len(self._pending.get(key, ()))

    async def submit(self, key: BatchKey, job: BatchJob) -> None:
        """Enqueue one job under its compatibility key. The caller awaits
        ``job.future``; this returns as soon as the job is parked (or
        dispatched, for the job that fills a batch)."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        bucket = self._pending.setdefault(key, [])
        bucket.append(job)
        if len(bucket) >= self.max_jobs:
            self.flush(key)
        elif len(bucket) == 1:
            self._timers[key] = self._timer(
                self.window_s, lambda: self.flush(key)
            )

    def flush(self, key: BatchKey) -> None:
        """Close the key's window and hand its jobs to dispatch (no-op if
        the bucket already flushed — timer/full-batch races are benign)."""
        jobs = self._pending.pop(key, None)
        timer = self._timers.pop(key, None)
        if timer is not None:
            cancel = getattr(timer, "cancel", None)
            if cancel is not None:
                cancel()
        if not jobs:
            return
        self.dispatched_batches += 1
        self.dispatched_jobs += len(jobs)
        task = asyncio.get_running_loop().create_task(
            self._run_dispatch(key, jobs)
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_dispatch(self, key: BatchKey, jobs: list[BatchJob]) -> None:
        try:
            await self._dispatch(key, jobs)
        except BaseException as e:  # noqa: BLE001 — promises must settle
            # The dispatcher's own contract is to settle every future
            # (including via serial fallback); anything escaping it is a
            # bug — fail the stragglers loudly rather than hanging their
            # requests forever.
            logger.exception("batch dispatch failed (lane=%d)", key.lane)
            for job in jobs:
                job.fail(e if isinstance(e, Exception) else RuntimeError(str(e)))
            if not isinstance(e, Exception):
                raise

    async def close(self) -> None:
        """Flush nothing, fail everything: shutdown semantics. In-flight
        dispatch tasks run to completion (they own sandbox cleanup)."""
        self._closed = True
        for key in list(self._pending):
            jobs = self._pending.pop(key, [])
            timer = self._timers.pop(key, None)
            if timer is not None:
                cancel = getattr(timer, "cancel", None)
                if cancel is not None:
                    cancel()
            for job in jobs:
                job.fail(
                    RuntimeError("service shutting down before dispatch")
                )
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
