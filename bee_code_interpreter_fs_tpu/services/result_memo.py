"""Content-addressed deterministic result memoization.

Tutorial and benchmark traffic at consumer scale is massively repetitive:
the same snippet, the same input files, the same limits — re-executed on a
chip that produces byte-identical output every time. A run that DECLARES
purity (no net, no randomness, no wall-clock reads — the client's promise,
echoed by the executor) and completed limit-clean is recorded here keyed by
everything that could change its output:

    (source sha256, input workspace-manifest sha, env key, limits key,
     chip-count lane, executor binary key)

and a later identical request is served from the record — no scheduler
ticket, no sandbox round-trip, no chip-second billed. This is the only
path to answers *faster* than the hardware.

Discipline is the fleet compile cache's (services/compile_cache.py),
applied verbatim:

- **Bytes are content-addressed** in a dedicated ``Storage`` (NOT the
  workspace-file store: eviction deletes objects, and sharing a store
  would let a memo eviction delete a workspace file's bytes out from
  under it). A record's output *files* stay in the workspace store —
  already content-addressed — and the record holds their object ids; a
  hit re-validates every referenced object before serving and demotes
  itself to a miss if any byte is gone.
- **The index rides ``StateStore``** (services/state_store.py), so memo
  hits are coherent across scale-out replicas exactly like scheduler
  grants and breaker verdicts: N replicas sharing one store share one
  memo. The in-memory default keeps single-replica behavior self-contained.
- **Per-tenant keying by default.** A tenant's recorded results serve only
  that tenant. Cross-tenant sharing exists but is provenance-gated: only
  control-plane-authored (trusted) runs may record into the shared scope,
  and only when ``APP_RESULT_MEMO_SHARED=1`` opted in — the compile
  cache's prewarm trust model.
- **First-write-wins with conflict accounting.** Two concurrent misses on
  one key admit the first record; a second record offering DIFFERENT
  result bytes under the same key is rejected and counted — a
  nondeterministic "pure" run at best, a poisoning attempt at worst.
  ``result_memo_conflicts_total`` moving is an investigate signal.
- **Kill switch** (``APP_RESULT_MEMO_ENABLED=0``): a disabled store does
  no IO, creates no directories, serves nothing, records nothing, and the
  executor stamps no phases keys — pre-memo behavior byte-for-byte.
- **Admission-order durability**: the record blob is made durable in
  Storage BEFORE the index entry is admitted, so a crash or wire drop
  mid-store can never leave an index entry pointing at partial bytes —
  the entry either serves a complete record or does not exist.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from dataclasses import dataclass
from pathlib import Path

from .storage import Storage, StorageObjectNotFound

logger = logging.getLogger(__name__)

# StateStore namespace the index rides (shared across PR 15 replicas).
MEMO_NS = "result_memo"

# Scope name for provenance-gated cross-tenant entries (never a valid
# tenant name: the scheduler's tenant charset forbids the leading dot).
SHARED_SCOPE = ".shared"

# Record wire/blob format version: bump on any change to the record blob
# or key derivation so stale entries miss instead of deserializing wrong.
RECORD_VERSION = 1

# Phases keys never recorded: per-request correlation/attribution state
# that must be THIS request's, not the recorded run's.
_EPHEMERAL_PHASES = frozenset({"trace_id", "quota", "memo"})


def _sha(parts: list[str]) -> str:
    h = hashlib.sha256()
    for part in parts:
        h.update(part.encode())
        h.update(b"\x00")
    return h.hexdigest()


def manifest_sha(files: dict[str, str] | None) -> str:
    """The input workspace-manifest sha: order-independent over
    (path, object id). Storage object ids ARE content sha256es (PR 3),
    so this keys the full input byte content without reading a byte."""
    entries = sorted((files or {}).items())
    return _sha([f"{path}={object_id}" for path, object_id in entries])


def mapping_sha(mapping: dict | None) -> str:
    """Order-independent key over a flat str->scalar mapping (env, limits)."""
    entries = sorted((mapping or {}).items())
    return _sha([f"{k}={v}" for k, v in entries])


def result_content_sha(
    stdout: str, stderr: str, exit_code: int, file_shas: list[str]
) -> str:
    """The canonical result hash — the same derivation the C++ executor
    computes for its `result_sha256` echo (executor/server.cpp), so the
    control plane can verify the wire block end-to-end before recording:
    sha256 over stdout, stderr, the decimal exit code, and the sorted
    changed-file content hashes, NUL-separated."""
    return _sha([stdout, stderr, str(int(exit_code)), *sorted(file_shas)])


def binary_key_of(executor_binary: str, executor_image: str) -> str:
    """The executor-binary component of every memo key: the content sha of
    the deployed binary when it is a readable local file (the local
    backend), else the image reference (kubernetes — the tag names the
    binary). Computed once per control-plane process; a binary upgrade
    changes the key and every old entry misses, which is the point."""
    path = executor_binary.strip()
    if path:
        try:
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            return f"bin:{h.hexdigest()}"
        except OSError:
            pass
    return f"img:{executor_image}"


@dataclass(frozen=True)
class MemoKey:
    """One request's memo identity: `scope` partitions tenants (trust),
    `digest` folds every output-determining input together."""

    scope: str
    digest: str

    @property
    def index_key(self) -> str:
        return f"{self.scope}/{self.digest}"


def derive_key(
    *,
    scope: str,
    source_code: str | None,
    source_file: str | None,
    files: dict[str, str] | None,
    env: dict[str, str] | None,
    limits: dict | None,
    lane: int,
    binary_key: str,
) -> MemoKey:
    source = (
        "code:" + hashlib.sha256((source_code or "").encode()).hexdigest()
        if source_code is not None
        else "file:" + (source_file or "")
    )
    digest = _sha(
        [
            f"v{RECORD_VERSION}",
            source,
            manifest_sha(files),
            mapping_sha(env),
            mapping_sha(limits),
            f"lane:{int(lane)}",
            binary_key,
        ]
    )
    return MemoKey(scope=scope, digest=digest)


class ResultMemoStore:
    """The memo itself: a StateStore-indexed, Storage-backed record set.

    Synchronous index bookkeeping (StateStore ops are dict/single-row
    SQLite statements), async byte movement — the compile-cache split.
    """

    def __init__(
        self,
        store_path: str | os.PathLike,
        state_store,
        workspace_storage: Storage | None,
        *,
        enabled: bool = True,
        shared: bool = False,
        max_bytes: int = 256 << 20,
        max_entries: int = 8192,
        clock=time.time,
        metrics=None,
    ) -> None:
        self.enabled = enabled
        self.shared = shared
        self.max_bytes = max(0, int(max_bytes))
        self.max_entries = max(0, int(max_entries))
        self.state = state_store
        self.workspace_storage = workspace_storage
        self._clock = clock
        self.metrics = metrics
        self.conflicts = 0
        self.hits = 0
        self.misses = 0
        if not enabled:
            # Kill switch: no directories, no state, every surface answers
            # empty — pre-memo behavior byte-for-byte.
            self.storage = None
            return
        self.path = Path(store_path)
        self.path.mkdir(parents=True, exist_ok=True)
        # Records live in their own Storage (NOT the workspace-file store):
        # memo eviction deletes objects, and sharing a store would let an
        # eviction delete a workspace file's bytes out from under it.
        self.storage = Storage(self.path / "objects")

    @classmethod
    def from_config(
        cls, config, state_store, workspace_storage, *, metrics=None
    ) -> "ResultMemoStore":
        path = config.result_memo_store_path or os.path.join(
            config.file_storage_path, ".result-memo"
        )
        return cls(
            path,
            state_store,
            workspace_storage,
            enabled=config.result_memo_enabled,
            shared=config.result_memo_shared,
            max_bytes=config.result_memo_max_bytes,
            max_entries=config.result_memo_max_entries,
            metrics=metrics,
        )

    # ------------------------------------------------------------------ index

    def entry_count(self) -> int:
        if not self.enabled:
            return 0
        return len(self.state.items(MEMO_NS))

    def total_bytes(self) -> int:
        if not self.enabled:
            return 0
        return sum(
            int(entry.get("size", 0))
            for entry in self.state.items(MEMO_NS).values()
            if isinstance(entry, dict)
        )

    def scopes_for(self, tenant_scope: str) -> list[str]:
        """Lookup order: the tenant's own scope first, then (when sharing
        is opted in) the provenance-gated shared scope."""
        scopes = [tenant_scope]
        if self.shared and tenant_scope != SHARED_SCOPE:
            scopes.append(SHARED_SCOPE)
        return scopes

    # ----------------------------------------------------------------- lookup

    async def lookup(self, key: MemoKey) -> dict | None:
        """The admission-path check: index entry -> record blob -> file
        validation. Any missing byte demotes to a miss and self-heals the
        index (the ProfileStore's stale-pointer rule). Never raises."""
        if not self.enabled:
            return None
        for scope in self.scopes_for(key.scope):
            index_key = f"{scope}/{key.digest}"
            entry = self.state.get(MEMO_NS, index_key)
            if not isinstance(entry, dict):
                continue
            record = await self._load_record(index_key, entry)
            if record is not None:
                self._touch(index_key)
                return record
        return None

    async def _load_record(self, index_key: str, entry: dict) -> dict | None:
        object_id = entry.get("record")
        if not isinstance(object_id, str):
            self.state.delete(MEMO_NS, index_key)
            return None
        try:
            blob = await self.storage.read(object_id)
            record = json.loads(blob)
        except (StorageObjectNotFound, OSError, ValueError):
            # Stale pointer (evicted/corrupt bytes under a live index row,
            # e.g. a replica's eviction racing this lookup): self-heal.
            self.state.delete(MEMO_NS, index_key)
            return None
        if (
            not isinstance(record, dict)
            or record.get("version") != RECORD_VERSION
        ):
            self.state.delete(MEMO_NS, index_key)
            return None
        # Output files live in the workspace store; a hit must never hand
        # the client object ids whose bytes are gone.
        files = record.get("files")
        if isinstance(files, dict) and self.workspace_storage is not None:
            for object_id in files.values():
                try:
                    if not await self.workspace_storage.exists(str(object_id)):
                        self.state.delete(MEMO_NS, index_key)
                        return None
                except (OSError, ValueError):
                    self.state.delete(MEMO_NS, index_key)
                    return None
        return record

    def _touch(self, index_key: str) -> None:
        now = self._clock()

        def bump(entry):
            if not isinstance(entry, dict):
                return entry, None
            entry = dict(entry)
            entry["hits"] = int(entry.get("hits", 0)) + 1
            entry["last_hit"] = round(now, 3)
            return entry, None

        try:
            self.state.mutate(MEMO_NS, index_key, bump)
        except Exception:  # noqa: BLE001 — recency is advisory
            logger.debug("memo touch failed", exc_info=True)

    # ----------------------------------------------------------------- record

    async def record(self, key: MemoKey, record: dict) -> str:
        """Admit one limit-clean pure run. Returns the outcome:
        ``admitted`` | ``exists`` (identical bytes already mapped) |
        ``conflict`` (different bytes under the key — first write wins) |
        ``error`` (bytes could not be made durable; nothing admitted).

        Durability order is the chaos-leg invariant: the record blob is
        written content-addressed (tmp + fsync + rename inside Storage)
        BEFORE the index mutate — a wire drop or crash mid-store leaves
        at worst an orphan object, never an index entry serving partial
        results."""
        if not self.enabled:
            return "error"
        record = dict(record)
        record["version"] = RECORD_VERSION
        record["created"] = round(self._clock(), 3)
        result_sha = record.get("result_sha", "")
        try:
            blob = json.dumps(record, sort_keys=True).encode()
            object_id = await self.storage.write(blob)
        except (OSError, ValueError):
            logger.warning("result memo record write failed", exc_info=True)
            return "error"

        index_key = key.index_key
        size = len(blob)
        now = round(self._clock(), 3)

        def admit(existing):
            if isinstance(existing, dict):
                if existing.get("result_sha") == result_sha:
                    return existing, "exists"
                # First-write-wins: the key already maps DIFFERENT bytes.
                return existing, "conflict"
            entry = {
                "record": object_id,
                "result_sha": result_sha,
                "size": size,
                "created": now,
                "last_hit": now,
                "hits": 0,
            }
            return entry, "admitted"

        try:
            outcome = self.state.mutate(MEMO_NS, index_key, admit)
        except Exception:  # noqa: BLE001
            logger.warning("result memo index admit failed", exc_info=True)
            return "error"
        if outcome == "conflict":
            self.conflicts += 1
            if self.metrics is not None:
                self.metrics.result_memo_conflicts.inc()
            logger.warning(
                "result memo conflict on %s: a declared-pure run produced "
                "different bytes than the recorded first write "
                "(nondeterministic source, or poisoning) — keeping the "
                "first record",
                index_key,
            )
        if outcome == "admitted":
            await self._evict()
        return outcome

    async def _evict(self) -> None:
        """LRU-by-last-hit eviction under both caps (compile-cache rule).
        Index first, bytes second: a concurrent replica's lookup either
        sees the entry (and may win the read race against the delete —
        content-addressed objects are immutable, so it serves correctly)
        or misses cleanly."""
        if not self.enabled or (not self.max_bytes and not self.max_entries):
            return
        while True:
            items = {
                k: v
                for k, v in self.state.items(MEMO_NS).items()
                if isinstance(v, dict)
            }
            over_entries = self.max_entries and len(items) > self.max_entries
            over_bytes = self.max_bytes and (
                sum(int(v.get("size", 0)) for v in items.values())
                > self.max_bytes
            )
            if not items or not (over_entries or over_bytes):
                return
            victim = min(
                items, key=lambda k: items[k].get("last_hit", 0.0)
            )
            object_id = items[victim].get("record")
            self.state.delete(MEMO_NS, victim)
            if isinstance(object_id, str):
                try:
                    await self.storage.delete(object_id)
                except (StorageObjectNotFound, OSError):
                    pass

    def snapshot(self) -> dict:
        """Operator view (GET /statusz companion data)."""
        if not self.enabled:
            return {"enabled": False}
        return {
            "enabled": True,
            "shared": self.shared,
            "entries": self.entry_count(),
            "bytes": self.total_bytes(),
            "hits": self.hits,
            "misses": self.misses,
            "conflicts": self.conflicts,
        }
