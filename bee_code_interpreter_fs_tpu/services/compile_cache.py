"""Fleet-wide persistent XLA compilation cache: the control plane's store
and the seed/harvest protocol against sandbox executors.

The bench trajectory (BENCH_r02-r05) shows the dominant cost of real array
workloads is JAX/XLA first-compile and accelerator page-in, not execution.
Per-sandbox ``JAX_COMPILATION_CACHE_DIR`` plumbing has existed since the
seed, but it was host-local at best and pod-local-and-dying on Kubernetes:
a million users running the same N popular kernels recompiled them once per
sandbox. This module applies the PR 3 content-addressed machinery to jit
artifacts so the fleet compiles each kernel exactly once:

- **Store** — JAX names every persistent-cache entry by a deterministic
  filename derived from its own cache key (``jit_<name>-<hash>-cache``), so
  the filename IS a stable fleet-wide identity. ``CompileCacheStore`` keeps
  a bounded hot set of those entries: bytes live in a content-addressed
  ``Storage`` (deduped by SHA-256 — identical executables from different
  sandboxes store once), an index maps entry name -> (sha, size, last_hit)
  and persists as JSON so the hot set survives control-plane restarts.
- **Seed at spawn** — every freshly spawned sandbox gets the hot set pushed
  into its cache dir before serving (GET /compile-cache-manifest to learn
  what the host already holds, conditional PUT for the rest — unchanged
  entries never cross the wire twice).
- **Harvest at turnover/teardown, TRUSTED PROVENANCE ONLY** — after a
  sandbox serves (generation turnover or disposal), entries it compiled
  that the store has never seen are pulled back (hash-negotiated: the
  manifest's sha is checked against the store before any bytes move).
  Admission is gated on provenance: a sandbox is harvestable only while
  every piece of code it has EVER run was control-plane-authored (the
  pre-warm kernel set). The moment tenant code executes on a sandbox its
  sync state is tainted for the sandbox's lifetime and harvest never
  touches it again — user code can write arbitrary bytes into
  ``JAX_COMPILATION_CACHE_DIR``, and a harvested artifact is a serialized
  XLA executable that every seeded sandbox would deserialize and run
  (cross-tenant code execution), while even a benignly compiled artifact
  can embed tenant data through constant folding (cross-tenant data
  leak). Tenant-compiled artifacts therefore never enter the fleet store,
  full stop; they still serve that one sandbox locally through its
  preserved cache dir. As a second line of defense the store is
  first-write-wins: a harvest manifest presenting different bytes under
  an entry name the store already maps is rejected, never admitted as a
  replacement.
- **Bounded hot set** — LRU by last hit with byte+entry caps, so seeding
  stays O(hot set), not O(history). Recency moves only on evidence of a
  real (re)compile: harvest admission, or a trusted sandbox presenting an
  entry the control plane did NOT seed into it (seeded entries reappear
  in every harvest manifest, so their re-observation proves nothing).
  The hot set self-heals across control-plane restarts: pre-warm runs on
  every start, so an evicted-but-still-prewarmed kernel is recompiled and
  re-admitted with fresh recency (one trusted recompile), while a kernel
  dropped from ``PREWARM_SOURCES`` is never refreshed again and ages to
  the LRU end.

A host that 404s the manifest route is remembered as legacy (old executor
binary) and is never probed again; the kill switch
(``APP_COMPILE_CACHE_ENABLED=0``) restores the exact pre-cache behavior (no
compile-cache HTTP at all).

Grounded in PAPERS.md ("Compiler-First State Space Duality and Portable
O(1) Autoregressive Caching", "Automatic Full Compilation ... to Cloud
TPUs"): compile-once/run-anywhere is the whole game on TPU.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections.abc import Callable
from dataclasses import dataclass, field
from pathlib import Path

import httpx

from ..utils.validation import SHA256_HEX_RE
from .storage import Storage, StorageObjectNotFound

logger = logging.getLogger(__name__)

# Entry names are JAX cache-key filenames (plus the -atime sidecars some
# jax versions keep). Anything path-traversal-ish is rejected outright —
# the name becomes a URL segment and a file path on both ends.
_BAD_NAME_PARTS = ("..", "\x00")

# Wire timeouts. Sync runs on spawn and TURNOVER paths: turnover of a dead
# or wedged sandbox must not park its lane's refill behind the shared
# client's 30s default — the manifest probe fails fast, which short-circuits
# the whole host. Entry bodies get longer (they stream real bytes).
MANIFEST_TIMEOUT = 5.0
ENTRY_TIMEOUT = 15.0


def valid_entry_name(rel: str) -> bool:
    if not rel or len(rel) > 512 or rel.startswith("/"):
        return False
    if rel.endswith("-atime"):
        # jax's per-host LRU sidecars (rewritten on every cache read):
        # local bookkeeping with no fleet meaning. The executor filters
        # them out of its manifest too — this guards against older ones.
        return False
    return not any(bad in rel for bad in _BAD_NAME_PARTS)


@dataclass
class SeedStats:
    """One sandbox's seeding outcome (summed across its hosts)."""

    pushed_files: int = 0
    pushed_bytes: int = 0
    skipped_files: int = 0  # host already held identical content
    skipped_bytes: int = 0


@dataclass
class HarvestStats:
    """One sandbox's harvest outcome (summed across its hosts)."""

    new_files: int = 0
    new_bytes: int = 0
    known_files: int = 0  # manifest entries the store already had
    discarded: int = 0  # bytes arrived but hash mismatched the manifest
    conflicts: int = 0  # entry name already mapped to DIFFERENT bytes


@dataclass
class _Entry:
    sha: str
    size: int
    last_hit: float
    hits: int = 0


class CompileCacheStore:
    """The fleet's hot set of compiled XLA executables.

    Synchronous on purpose: every operation is index bookkeeping (byte
    movement happens through the async ``Storage``); callers hold no lock
    because the control plane is one asyncio thread (the scale-out ROADMAP
    item moves this behind the same shared-store interface as the
    scheduler state).
    """

    INDEX_NAME = "index.json"

    def __init__(
        self,
        store_path: str | os.PathLike,
        *,
        max_bytes: int = 1 << 30,
        max_entries: int = 4096,
        enabled: bool = True,
        clock=time.time,
    ) -> None:
        self.enabled = enabled
        self.max_bytes = max(0, int(max_bytes))
        self.max_entries = max(0, int(max_entries))
        self._clock = clock
        self.path = Path(store_path)
        self._entries: dict[str, _Entry] = {}
        # True whenever the entry map has mutated since the last successful
        # save — new admissions, dedup mappings AND evictions (eviction
        # deletes storage objects, so an unsaved index would reference bytes
        # the store no longer holds after a restart).
        self._dirty = False
        if not enabled:
            # Kill switch: no directories created, no state, every surface
            # answers empty — exact pre-cache behavior.
            self.storage = None
            return
        self.path.mkdir(parents=True, exist_ok=True)
        # Objects live in their own Storage (NOT the workspace-file store):
        # eviction deletes objects, and sharing a store would let a cache
        # eviction delete a workspace file's bytes out from under it.
        self.storage = Storage(self.path / "objects")
        self._load_index()

    @classmethod
    def from_config(cls, config) -> "CompileCacheStore":
        path = config.compile_cache_store_path or os.path.join(
            config.file_storage_path, ".compile-cache"
        )
        return cls(
            path,
            max_bytes=config.compile_cache_max_bytes,
            max_entries=config.compile_cache_max_entries,
            enabled=config.compile_cache_enabled,
        )

    # ------------------------------------------------------------- index IO

    def _load_index(self) -> None:
        try:
            raw = json.loads((self.path / self.INDEX_NAME).read_text())
        except (OSError, ValueError):
            return
        if not isinstance(raw, dict):
            return
        for rel, entry in raw.items():
            if not (isinstance(rel, str) and valid_entry_name(rel)):
                continue
            if not isinstance(entry, dict):
                continue
            sha = entry.get("sha")
            if not (isinstance(sha, str) and SHA256_HEX_RE.match(sha)):
                continue
            try:
                self._entries[rel] = _Entry(
                    sha=sha,
                    size=max(0, int(entry.get("size", 0))),
                    last_hit=float(entry.get("last_hit", 0.0)),
                    hits=max(0, int(entry.get("hits", 0))),
                )
            except (TypeError, ValueError):
                continue

    def save_index(self) -> None:
        """Atomic index persist (tmp + rename), best-effort: a failed save
        costs warm-start continuity, never correctness."""
        if not self.enabled:
            return
        blob = {
            rel: {
                "sha": e.sha,
                "size": e.size,
                "last_hit": e.last_hit,
                "hits": e.hits,
            }
            for rel, e in self._entries.items()
        }
        tmp = self.path / (self.INDEX_NAME + ".tmp")
        try:
            tmp.write_text(json.dumps(blob))
            os.replace(tmp, self.path / self.INDEX_NAME)
        except OSError:
            logger.warning("compile-cache index save failed", exc_info=True)
        else:
            self._dirty = False

    @property
    def dirty(self) -> bool:
        """Entry map mutated since the last successful save_index()."""
        return self._dirty

    # ------------------------------------------------------------- hot set

    def manifest(self) -> dict[str, str]:
        """The hot set as entry-name -> sha (what seeding pushes)."""
        if not self.enabled:
            return {}
        return {rel: e.sha for rel, e in self._entries.items()}

    def sha_of(self, rel: str) -> str | None:
        entry = self._entries.get(rel)
        return entry.sha if entry is not None else None

    def total_bytes(self) -> int:
        return sum(e.size for e in self._entries.values())

    def entry_count(self) -> int:
        return len(self._entries)

    def touch(self, rel: str) -> None:
        entry = self._entries.get(rel)
        if entry is not None:
            entry.last_hit = self._clock()
            entry.hits += 1
            self._dirty = True

    async def record(self, rel: str, sha: str, size: int) -> list[str]:
        """Admit a harvested entry (bytes already in storage under `sha`)
        and enforce the hot-set bounds. Returns the evicted entry names."""
        if not self.enabled or not valid_entry_name(rel):
            return []
        self._entries[rel] = _Entry(
            sha=sha, size=max(0, int(size)), last_hit=self._clock(), hits=1
        )
        self._dirty = True
        return await self._evict_over_caps()

    async def _evict_over_caps(self) -> list[str]:
        """LRU-by-last-hit eviction down to the byte/entry caps. Storage
        objects are deleted only when no surviving entry references the sha
        (distinct entry names can dedup onto identical bytes)."""
        evicted: list[str] = []
        while self._entries and (
            (self.max_entries and len(self._entries) > self.max_entries)
            or (self.max_bytes and self.total_bytes() > self.max_bytes)
        ):
            rel = min(self._entries, key=lambda r: self._entries[r].last_hit)
            entry = self._entries.pop(rel)
            evicted.append(rel)
            self._dirty = True
            if not any(e.sha == entry.sha for e in self._entries.values()):
                try:
                    await self.storage.delete(entry.sha)
                except OSError:
                    pass
        return evicted

    async def drop_unverified(self, sha: str) -> None:
        """A harvested body hashed to `sha` but the manifest promised
        something else (mid-transfer drop, racing rewrite): the object must
        not linger as an orphan unless another entry legitimately owns it."""
        if self.storage is None:
            return
        if not any(e.sha == sha for e in self._entries.values()):
            try:
                await self.storage.delete(sha)
            except OSError:
                pass


class HostCacheState:
    """What the control plane knows about one sandbox host's compile-cache
    dir. Mirrors transfer.HostManifest's tri-state: ``supports`` is None
    until observed, True after any manifest answer, False once a 404 proves
    the host legacy (an old binary without the endpoints) — after which no
    compile-cache HTTP is ever attempted again for that host."""

    __slots__ = ("present", "supports", "seeded")

    def __init__(self) -> None:
        self.present: dict[str, str] = {}
        self.supports: bool | None = None
        # Entry names whose host copy the store is KNOWN to agree with —
        # seeded into it, confirmed present at seed time, or admitted
        # from it by an earlier harvest. Their reappearance in a harvest
        # manifest is NOT evidence of a recompile (the cache dir outlives
        # /reset), so harvest must not refresh their recency.
        self.seeded: set[str] = set()

    def mark_legacy(self) -> None:
        self.present = {}
        self.seeded = set()
        self.supports = False


class SandboxCacheSync:
    """Per-sandbox compile-cache sync state + the wire protocol.

    Rides in ``Sandbox.meta`` (like SandboxTransfer) so it follows the
    sandbox through pool recycles and session parking. The cache dir is
    deliberately NOT wiped by /reset, so ``present`` stays valid across
    generations.
    """

    def __init__(
        self,
        store: CompileCacheStore,
        *,
        harvest_allowed: Callable[[], bool] | None = None,
    ) -> None:
        self.store = store
        # Control-plane-level trust gate, re-evaluated MID-harvest: on a
        # shared cache dir the writer that revokes trust is a different
        # sandbox, so the revocation can land while this sandbox's harvest
        # is awaiting the network — every admission re-checks it (see
        # _trust_revoked) so bytes written after the revocation can never
        # be admitted. None = only per-sandbox taint gates.
        self._harvest_allowed = harvest_allowed
        self._hosts: dict[str, HostCacheState] = {}
        # Surfaced into the first Result.phases after a seed (the request
        # that popped this freshly seeded sandbox reports what seeding it
        # cost) — see CodeExecutor._run_on_sandbox.
        self.pending_seed_bytes: int | None = None
        # Provenance gate for harvest. False only while every piece of code
        # this sandbox has ever run was control-plane-authored (pre-warm);
        # flips True — permanently, the cache dir outlives /reset — the
        # moment tenant code executes. A tainted sandbox's cache dir is
        # attacker-writable, and harvested entries are serialized XLA
        # executables the fleet would deserialize and run, so harvest
        # refuses it outright (not even a manifest probe).
        self.tainted = False

    def taint(self) -> None:
        self.tainted = True

    def _trust_revoked(self) -> bool:
        """Harvest trust as of RIGHT NOW. Checked at every await boundary
        that can admit bytes, not just at harvest entry: the taint (per
        sandbox or control-plane-wide via `harvest_allowed`) is set before
        the tainting tenant code runs, so any cache-dir write that code
        makes strictly follows the flag — a re-check immediately before
        admission therefore can never admit a post-revocation write, even
        when the revocation landed mid-harvest."""
        if self.tainted:
            return True
        return self._harvest_allowed is not None and not self._harvest_allowed()

    def host(self, base_url: str) -> HostCacheState:
        state = self._hosts.get(base_url)
        if state is None:
            state = HostCacheState()
            self._hosts[base_url] = state
        return state

    # -------------------------------------------------------------- protocol

    async def _fetch_manifest(
        self, client: httpx.AsyncClient, base: str, state: HostCacheState
    ) -> dict[str, str] | None:
        """GET /compile-cache-manifest; None = host unusable this round
        (legacy, disabled, or transient failure)."""
        try:
            resp = await client.get(
                f"{base}/compile-cache-manifest", timeout=MANIFEST_TIMEOUT
            )
        except httpx.HTTPError:
            return None
        if resp.status_code == 404:
            # Old binary (or compile cache disabled server-side): remembered
            # forever, exactly like the workspace-manifest fallback.
            state.mark_legacy()
            return None
        if resp.status_code != 200:
            return None
        try:
            files = resp.json().get("files", {})
        except ValueError:
            return None
        if not isinstance(files, dict):
            return None
        manifest = {
            rel: sha
            for rel, sha in files.items()
            if isinstance(rel, str)
            and valid_entry_name(rel)
            and isinstance(sha, str)
            and SHA256_HEX_RE.match(sha)
        }
        state.supports = True
        state.present = dict(manifest)
        return manifest

    async def seed_host(
        self, client: httpx.AsyncClient, base: str
    ) -> SeedStats:
        """Push the store's hot set into one host's cache dir. Entries the
        host already holds (manifest match or conditional-PUT 304) move no
        bytes. Failures degrade to fewer seeded entries, never to errors —
        a missed seed costs one recompile, not a request."""
        stats = SeedStats()
        if not self.store.enabled:
            return stats
        hot = self.store.manifest()
        if not hot:
            return stats
        state = self.host(base)
        if state.supports is False:
            return stats
        remote = await self._fetch_manifest(client, base, state)
        if remote is None:
            return stats
        for rel, sha in hot.items():
            size = 0
            try:
                size = await self.store.storage.size(sha)
            except (StorageObjectNotFound, ValueError):
                continue  # index ahead of storage (crash window): skip
            if remote.get(rel) == sha:
                state.seeded.add(rel)
                stats.skipped_files += 1
                stats.skipped_bytes += size
                continue
            if await self._put_entry(client, base, rel, sha):
                state.present[rel] = sha
                state.seeded.add(rel)
                stats.pushed_files += 1
                stats.pushed_bytes += size
                # Deliberately NOT a last_hit touch: every fresh sandbox
                # lacks everything, so a per-push refresh would flatten the
                # LRU signal across the whole hot set on every spawn.
                # last_hit moves only on evidence of a real (re)compile —
                # harvest admission, or a trusted run presenting an entry
                # this host was never seeded (state.seeded) — and the hot
                # set self-heals across restarts via the per-start
                # pre-warm (evicted-but-kept kernels re-admit; dropped
                # kernels age to the LRU end).
        return stats

    async def _put_entry(
        self, client: httpx.AsyncClient, base: str, rel: str, sha: str
    ) -> bool:
        async def stream():
            async with self.store.storage.reader(sha) as reader:
                while True:
                    data = await reader.read(1 << 20)
                    if not data:
                        return
                    yield data

        try:
            resp = await client.put(
                f"{base}/compile-cache/{rel}",
                content=stream(),
                headers={"If-None-Match": sha},
                timeout=ENTRY_TIMEOUT,
            )
        except httpx.HTTPError:
            return False
        # 304 = host already held these exact bytes; both count as present.
        return resp.status_code in (200, 304)

    async def harvest_host(
        self, client: httpx.AsyncClient, base: str
    ) -> HarvestStats:
        """Pull entries this host compiled that the store has never seen.
        Hash-negotiated: a manifest entry whose sha the store (or another
        entry) already holds moves no bytes. A body that does not hash to
        its promised sha (connection drop mid-stream surfaces as an httpx
        error; a racing rewrite as a mismatch) is discarded — no partial or
        orphan objects, ever.

        Trust boundary: refuses tainted sandboxes entirely (see ``tainted``)
        and is first-write-wins per entry name — a manifest presenting
        different bytes under a name the store already maps is a conflict,
        never a replacement (a rename-an-attack-under-a-known-identity
        channel, and in the benign case a nondeterministic recompile the
        fleet has no reason to prefer)."""
        stats = HarvestStats()
        if not self.store.enabled or self._trust_revoked():
            return stats
        state = self.host(base)
        if state.supports is False:
            return stats
        manifest = await self._fetch_manifest(client, base, state)
        if manifest is None:
            return stats
        for rel, sha in manifest.items():
            if self._trust_revoked():
                # Revoked while this harvest was awaiting the network (a
                # tenant run started on a sandbox sharing this cache dir):
                # everything not yet admitted stays out.
                logger.info(
                    "compile-cache harvest of %s stopped mid-flight: "
                    "trust revoked",
                    base,
                )
                break
            known_sha = self.store.sha_of(rel)
            if known_sha == sha:
                if rel not in state.seeded:
                    # Present on the host but NOT because we seeded it (or
                    # harvested it earlier): a trusted run genuinely
                    # (re)compiled this entry, so refresh its recency —
                    # once. Known entries reappear in every later harvest
                    # manifest of this host (the cache dir outlives
                    # /reset), so without marking them seeded here each
                    # re-observation would re-touch with no recompile and
                    # flatten the LRU signal to nothing.
                    self.store.touch(rel)
                    state.seeded.add(rel)
                stats.known_files += 1
                continue
            if known_sha is not None:
                self._note_conflict(base, rel, stats)
                continue
            if await self.store.storage.exists(sha):
                # Dedup: bytes already stored (same executable under a
                # different entry name, or a previous harvest) — record the
                # mapping without moving anything.
                size = await self.store.storage.size(sha)
                if await self._admit(base, rel, sha, size, stats, state):
                    stats.known_files += 1
                continue
            got = await self._get_entry(client, base, rel)
            if got is None:
                continue
            actual_sha, size = got
            if actual_sha != sha:
                # The manifest promised different content: never admit it
                # under the promised identity, never leave the stray bytes.
                await self.store.drop_unverified(actual_sha)
                stats.discarded += 1
                continue
            if await self._admit(base, rel, sha, size, stats, state):
                stats.new_files += 1
                stats.new_bytes += size
        return stats

    async def _admit(
        self,
        base: str,
        rel: str,
        sha: str,
        size: int,
        stats: HarvestStats,
        state: HostCacheState,
    ) -> bool:
        """Final admission, re-checking the store IMMEDIATELY before
        record(): harvest_host awaits the network between its first
        conflict check and this point, and two sandboxes' turnover
        harvests can race the same entry name (e.g. a nondeterministic
        recompile of the same kernel on two untainted sandboxes).
        First-write-wins must hold across that window too — without the
        re-check the loser would silently REPLACE the winner's mapping
        and orphan its storage object forever (no surviving entry
        references it, so eviction's refcount check never deletes it).
        No awaits run between the re-check and record()'s entry-map
        mutation, so the decision cannot go stale. Returns True when
        `rel` was recorded; on a lost race the bytes are dropped unless
        another entry owns them, and stats are counted here.

        Whenever the store ends up mapping rel -> sha (recorded here, or
        a lost race to identical bytes), the host is marked seeded for
        `rel`: this host's copy and the store's now agree, so its
        reappearance in later harvest manifests of the same host proves
        no recompile and must not re-touch recency."""
        if self._trust_revoked():
            # Trust revoked between the loop's check and this admission
            # (the entry download awaited the network): the bytes may
            # postdate the revoking tenant run, so they must not enter
            # the store — drop them unless another entry owns them.
            await self.store.drop_unverified(sha)
            return False
        current = self.store.sha_of(rel)
        if current == sha:
            state.seeded.add(rel)
            stats.known_files += 1
            return False
        if current is not None:
            self._note_conflict(base, rel, stats)
            await self.store.drop_unverified(sha)
            return False
        await self.store.record(rel, sha, size)
        state.seeded.add(rel)
        return True

    @staticmethod
    def _note_conflict(base: str, rel: str, stats: HarvestStats) -> None:
        """The single first-write-wins rejection point: both the loop's
        pre-download check and _admit's post-download re-check land here,
        so conflict policy/accounting cannot drift between them."""
        logger.warning(
            "compile-cache harvest conflict: %s offered different bytes "
            "for %s; keeping the store's copy",
            base,
            rel,
        )
        stats.conflicts += 1

    async def _get_entry(
        self, client: httpx.AsyncClient, base: str, rel: str
    ) -> tuple[str, int] | None:
        try:
            async with client.stream(
                "GET", f"{base}/compile-cache/{rel}", timeout=ENTRY_TIMEOUT
            ) as resp:
                if resp.status_code != 200:
                    # Checked BEFORE the writer opens: returning from inside
                    # an open writer context would finalize it and commit a
                    # stray empty object no index entry references.
                    return None
                async with self.store.storage.writer() as writer:
                    async for chunk in resp.aiter_bytes():
                        await writer.write(chunk)
        except httpx.HTTPError:
            # Mid-stream drop: the writer context already unlinked its tmp
            # file — nothing partial reaches the object dir.
            return None
        assert writer.hash is not None
        return writer.hash, writer.size

    async def seed(self, client: httpx.AsyncClient, hosts: list[str]) -> SeedStats:
        total = SeedStats()
        results = await asyncio.gather(
            *(self.seed_host(client, base) for base in hosts),
            return_exceptions=True,
        )
        for result in results:
            if isinstance(result, BaseException):
                logger.warning("compile-cache seed failed: %r", result)
                continue
            total.pushed_files += result.pushed_files
            total.pushed_bytes += result.pushed_bytes
            total.skipped_files += result.skipped_files
            total.skipped_bytes += result.skipped_bytes
        return total

    async def harvest(
        self, client: httpx.AsyncClient, hosts: list[str]
    ) -> HarvestStats:
        total = HarvestStats()
        if not self.store.enabled or self._trust_revoked():
            return total
        # Sequential across a slice group's hosts on purpose: peers of one
        # slice compiled the same kernels, so host 0's harvest makes every
        # peer's entries dedup to known_files instead of racing N identical
        # downloads.
        for base in hosts:
            try:
                result = await self.harvest_host(client, base)
            except Exception:  # noqa: BLE001 — harvest is best-effort
                logger.warning("compile-cache harvest failed", exc_info=True)
                continue
            total.new_files += result.new_files
            total.new_bytes += result.new_bytes
            total.known_files += result.known_files
            total.discarded += result.discarded
            total.conflicts += result.conflicts
        # Persist on ANY entry-map mutation — dedup admissions (new entry
        # name onto already-stored bytes) and evictions mutate state without
        # moving new bytes, and an unsaved index would resurrect deleted
        # objects / lose mappings across a control-plane restart.
        if self.store.dirty:
            self.store.save_index()
        return total


# The pool-fill pre-warm kernel set: the core XLA kernels the `examples/`
# workloads exercise (benchmark-matmul.py's jit matmul, benchmark-numpy.py's
# elementwise/reduction chains), distilled to single-compile snippets so a
# pre-warm costs seconds, not a full benchmark run. Each snippet compiles
# with the sandbox's persistent cache armed, so its executable lands in the
# cache dir and the post-execute harvest admits it to the fleet store.
# These runs are the fleet store's ONLY admission source: they execute as
# trusted (control-plane-authored) code on untainted sandboxes, which is
# what makes their harvest safe to seed into every tenant's sandbox.
PREWARM_SOURCES: list[tuple[str, str]] = [
    (
        "matmul",
        """
import jax, jax.numpy as jnp
f = jax.jit(lambda a, b: a @ b)
x = jnp.ones((256, 256), dtype=jnp.float32)
f(x, x).block_until_ready()
print("prewarm matmul ok")
""",
    ),
    (
        "elementwise",
        """
import jax, jax.numpy as jnp
f = jax.jit(lambda a: jnp.tanh(a) * 2.0 + 1.0)
f(jnp.ones((1024,), dtype=jnp.float32)).block_until_ready()
print("prewarm elementwise ok")
""",
    ),
    (
        "reduction",
        """
import jax, jax.numpy as jnp
f = jax.jit(lambda a: jnp.sum(a, axis=-1))
f(jnp.ones((256, 256), dtype=jnp.float32)).block_until_ready()
print("prewarm reduction ok")
""",
    ),
    (
        # The batched-execution-lanes dispatch shape: shard_map over a
        # 1-axis "jobs" mesh (parallel/mesh.job_mesh's layout), one job's
        # matmul block per device — what a fused multi-chip dispatch
        # compiles. Warm fleet-wide, the first batch of a shape loads from
        # cache instead of eating an XLA compile inside the batching
        # window. Version-defensive shard_map resolution mirrors
        # parallel/mesh.shard_map (the snippet must stand alone in the
        # sandbox, where this package is not importable).
        "batched_dispatch",
        """
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import Mesh, PartitionSpec as P
devs = jax.devices()
mesh = Mesh(np.array(devs), ("jobs",))
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map
f = jax.jit(
    _shard_map(
        lambda a, b: a @ b,
        mesh=mesh,
        in_specs=(P("jobs"), P("jobs")),
        out_specs=P("jobs"),
    )
)
n = len(devs)
x = jnp.ones((n * 128, 128), dtype=jnp.float32)
y = jnp.ones((n * 128, 128), dtype=jnp.float32)
f(x, y).block_until_ready()
print("prewarm batched_dispatch ok", n)
""",
    ),
    (
        # The batch bench's hot small-array shape (scripts/bench_batch.py:
        # a chained 64x64 matmul — the coalesced small-job workload the
        # batching lanes exist for), jitted so the whole chain compiles to
        # ONE cached executable. Fleet coverage scales only with this set
        # (pre-warm is the store's sole admission source), and a cold
        # lane's first burst of small jobs is exactly when an XLA compile
        # inside the batching window hurts most.
        "small_matmul_chain",
        """
import jax, jax.numpy as jnp

@jax.jit
def chain(x, y):
    for _ in range(4):
        x = x @ y
    return x

x = jnp.ones((64, 64), dtype=jnp.float32)
y = jnp.eye(64, dtype=jnp.float32)
chain(x, y).block_until_ready()
print("prewarm small_matmul_chain ok")
""",
    ),
]
