"""Control-plane error hierarchy, shared by the executor, the circuit
breaker, and both API servers.

Lives in its own module so `services/circuit_breaker.py` can raise a
retryable `SessionLimitError` subclass without importing the executor (which
imports the breaker — a cycle otherwise). `services/code_executor.py`
re-exports everything here, so existing importers keep working.
"""

from __future__ import annotations


class ExecutorError(RuntimeError):
    """Infrastructure-level execution failure (retried, then surfaced)."""


class LimitExceededError(RuntimeError):
    """A sandbox resource limit ended the execution: the executor killed the
    runner group (or its in-process guard unwound user code) and reported a
    typed violation. DETERMINISTIC — the same snippet breaches the same
    budget every time — so deliberately NOT an ExecutorError subclass: the
    retry ladder must never replay it against a fresh sandbox. Maps to HTTP
    422 (the request is well-formed but unprocessable within its budget)
    and gRPC RESOURCE_EXHAUSTED, both carrying the violation kind.

    ``kind`` is one of services.limits.VIOLATION_KINDS; ``continuable`` is
    True when the warm process survived (an in-process guard fired — e.g.
    cpu_time via SIGXCPU), False when the runner group was killed, which is
    what arms the repeat-offender path (host disposed, lane breaker
    strike)."""

    def __init__(
        self,
        message: str,
        *,
        kind: str,
        lane: int = 0,
        continuable: bool = False,
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.lane = lane
        self.continuable = continuable


class StaleLeaseError(ExecutorError):
    """A dispatch carried (or would carry) a lease token its host no longer
    honors: the control plane fenced the (host, chip-set) lease — a wedged
    verdict bumped the generation — so this claim must never touch those
    chips again. Raised in two places: by the control plane BEFORE the wire
    hop when the sandbox's own lease is already revoked (a fence raced an
    in-flight request), and on the executor's typed ``409 stale_lease``
    refusal (a late claim reached a successor holding a newer generation).

    A clean refusal: nothing ran on the device (``device_may_have_run``
    False exempts it from fault billing), and the rejected sandbox handle
    is disposed, never recycled. An ExecutorError subclass ON PURPOSE: the
    stateless retry ladder may replay the request — each attempt acquires
    a FRESH sandbox, so the retry lands on a healthy successor, never
    against the fenced host — and sessions get the standard
    close-session-and-surface semantics, which is exactly "end the session
    with a typed retryable error so the client can reconnect". Maps to
    HTTP 409 + Retry-After / gRPC ABORTED when it does surface."""

    device_may_have_run = False

    def __init__(
        self, message: str, *, scope: str = "", retry_after: float = 1.0
    ) -> None:
        super().__init__(message)
        self.scope = scope
        self.retry_after = retry_after


class SessionRestoringError(ExecutorError):
    """The session is mid-restore from its durable checkpoint (session
    durability plane, services/session_store.py): one turn already owns the
    restore — a second turn admitted now would race a double-restore into
    the same sandbox. A typed, retryable refusal, NOT a session-ending
    fault: the session stays live and the restore finishes without the
    loser. Maps to HTTP 409 + Retry-After (the stale-lease family — the
    client's existing 409 retry loop needs no new branch) and gRPC
    UNAVAILABLE with ``x-session-restoring`` trailing metadata."""

    def __init__(
        self, message: str, *, executor_id: str = "", retry_after: float = 1.0
    ) -> None:
        super().__init__(message)
        self.executor_id = executor_id
        self.retry_after = retry_after


class SessionLimitError(RuntimeError):
    """All executor_id session slots are in use (retryable: HTTP 429 /
    gRPC RESOURCE_EXHAUSTED — not a defect in the request itself)."""


class CapacityTimeoutError(SessionLimitError):
    """A request waited ``executor_acquire_timeout`` seconds for a sandbox
    slot without one turning over — e.g. a capacity-constrained TPU lane
    whose every chip is held by actively-used sessions. Subclasses
    SessionLimitError so both API layers already map it to a retryable
    HTTP 429 / gRPC RESOURCE_EXHAUSTED instead of the caller hanging
    indefinitely (ADVICE r3 #1)."""


class AdmissionRejectedError(SessionLimitError):
    """The scheduler refused the request AT ADMISSION (arrival time), before
    it spent any of the acquire budget queueing. Retryable — HTTP 429 /
    gRPC RESOURCE_EXHAUSTED via the SessionLimitError parent — but unlike
    the parent it always carries a COMPUTED ``retry_after`` (derived from
    current queue depth and the lane's EWMA wait), which the HTTP layer
    surfaces as a ``Retry-After`` header so clients back off proportionally
    to the actual backlog instead of guessing."""

    def __init__(
        self,
        message: str,
        *,
        lane: int = 0,
        tenant: str = "",
        retry_after: float = 0.0,
    ) -> None:
        super().__init__(message)
        self.lane = lane
        self.tenant = tenant
        self.retry_after = retry_after


class QueueDepthError(AdmissionRejectedError):
    """The tenant's per-lane queue-depth bound is full: admitting one more
    request would let a flooding tenant build unbounded backlog (and
    unbounded queue-wait for everyone behind it). Shed at arrival with a
    Retry-After that grows with the lane's total queue depth."""


class DeadlineInfeasibleError(AdmissionRejectedError):
    """The request's start deadline cannot beat the estimated queue wait
    (EWMA of recent queue-wait + spawn latency), so it is rejected on
    arrival instead of being parked until the deadline (or the 300s acquire
    budget) expires — the client learns immediately and can retry
    elsewhere."""


class QuotaExceededError(AdmissionRejectedError):
    """The quota layer (services/quotas.py) refused the request at the door,
    BEFORE the scheduler ever saw it: the tenant is over its sliding-window
    chip-second budget, its request-rate or concurrent-grant cap, or is
    quarantined as a repeat limit-violation offender. Retryable for the
    budget/rate/concurrency reasons — HTTP 429 / gRPC RESOURCE_EXHAUSTED
    with a Retry-After computed from the window's refill point and
    ``x-quota-*`` metadata naming the reason and the remaining budget.
    ``reason == "quarantined"`` is the shedding half: the same family (the
    client's retry loop needs no new branch) with a distinct reason, and the
    request is never enqueued — zero sandboxes, zero scheduler state, zero
    chip-seconds burned per rejected attempt."""

    def __init__(
        self,
        message: str,
        *,
        tenant: str = "",
        reason: str = "chip_seconds",
        retry_after: float = 0.0,
        remaining_chip_seconds: float | None = None,
        limit_chip_seconds: float | None = None,
        window_seconds: float | None = None,
        remaining_hbm_byte_seconds: float | None = None,
        limit_hbm_byte_seconds: float | None = None,
        burst_credits_remaining: float | None = None,
    ) -> None:
        super().__init__(
            message, lane=0, tenant=tenant, retry_after=retry_after
        )
        self.reason = reason
        self.remaining_chip_seconds = remaining_chip_seconds
        self.limit_chip_seconds = limit_chip_seconds
        self.window_seconds = window_seconds
        # HBM budget denials (reason="hbm_byte_seconds") carry the memory
        # window's remaining/limit; burst-mode denials
        # (reason="burst_credits") carry the bucket level — each rides its
        # own X-Quota-* header so pacing clients can tell the budgets apart.
        self.remaining_hbm_byte_seconds = remaining_hbm_byte_seconds
        self.limit_hbm_byte_seconds = limit_hbm_byte_seconds
        self.burst_credits_remaining = burst_credits_remaining


class StateStoreDegradedError(RuntimeError):
    """The shared control-plane StateStore is unreachable and the subsystem
    that needed it FAILS CLOSED (services/state_store.py degraded-mode
    policy): lease mints (a partitioned replica granting chips off a stale
    generation counter could double-grant hardware a peer already granted
    or fenced) and session hibernate/restore (restoring blind against an
    unreadable checkpoint index would fork session state). Deliberately NOT
    an ExecutorError: the retry ladder must not replay inside the same
    outage window — the client backs off on the carried ``retry_after``
    (the store health breaker's next probe point) instead. Maps to HTTP 503
    + Retry-After with a typed body, and gRPC UNAVAILABLE with
    ``x-store-degraded`` trailing metadata. Fail-OPEN subsystems (scheduler
    WFQ, breaker verdicts, quota accrual) never raise this — they fall back
    to replica-local shadow state and reconcile on reconnect."""

    def __init__(
        self, message: str, *, subsystem: str = "", retry_after: float = 5.0
    ) -> None:
        super().__init__(message)
        self.subsystem = subsystem
        self.retry_after = retry_after


class CircuitOpenError(SessionLimitError):
    """The lane's spawn circuit breaker is open: the backend failed N
    consecutive spawns and the cooldown has not elapsed, so the request
    fails fast instead of burning its acquire budget against a backend
    that is down. Retryable, but mapped DISTINCTLY from its
    SessionLimitError parent on both API surfaces: HTTP 503 + Retry-After
    and gRPC UNAVAILABLE (degraded service), versus the parent's 429 /
    RESOURCE_EXHAUSTED (healthy service, caller hit a capacity cap). The
    subclass relationship is the safety net — an unanticipated path that
    only knows SessionLimitError still returns something retryable."""

    def __init__(
        self, message: str, *, lane: int = 0, retry_after: float = 0.0
    ) -> None:
        super().__init__(message)
        self.lane = lane
        self.retry_after = retry_after
