"""Consistent-hash session→replica affinity for the scale-out control plane.

With N stateless replicas behind one Service, stateless requests may land
anywhere — but a SESSION (``executor_id``) parks a live sandbox on the
replica that created it, and in-flight grants belong to one scheduler. The
edge therefore hashes ``(tenant, executor_id)`` onto a consistent-hash ring
over the replica set: the owner serves locally; every other replica either
transparently proxies the request to the owner or answers a 307 redirect
carrying ``X-Replica-Owner`` (``APP_REPLICA_PROXY=0``), so session-parked
sandboxes and their grants stay single-owner while stateless traffic
load-balances freely.

Membership: the static peer list (``APP_REPLICA_PEERS``, e.g. the pod names
a k8s headless Service resolves) intersected with LIVENESS — each replica
heartbeats into the shared state store, and a peer whose heartbeat goes
stale past the TTL drops off the ring, so its sessions REHASH onto the
survivors (the failover story: a killed replica's sessions are served by
whoever now owns their hash, after lease-fenced turnover of the dead
owner's hosts). A proxy-level connection failure marks the peer dead
immediately (a crashed process stops answering before its heartbeat
expires) for one TTL.

Consistent hashing (vnodes on a sha256 ring) keeps the reshuffle minimal:
a replica joining or leaving moves ~1/N of the session keys, not all of
them.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from bisect import bisect_right
from collections.abc import Callable

logger = logging.getLogger(__name__)

_VNODES = 64


def _hash(value: str) -> int:
    return int.from_bytes(
        hashlib.sha256(value.encode("utf-8")).digest()[:8], "big"
    )


def parse_peers(spec: str) -> dict[str, str]:
    """``APP_REPLICA_PEERS`` grammar: comma-separated peers, each either
    ``id=http://host:port`` or ``host:port`` (the id then defaults to the
    host:port string). Returns {replica_id: base_url}."""
    peers: dict[str, str] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" in entry:
            rid, _, addr = entry.partition("=")
            rid = rid.strip()
            addr = addr.strip()
        else:
            rid, addr = entry, entry
        if not addr.startswith(("http://", "https://")):
            addr = f"http://{addr}"
        peers[rid] = addr.rstrip("/")
    return peers


class ReplicaRing:
    """The hash ring over live replicas.

    ``self_id`` must be one of the peers (or the ring degrades to
    single-replica mode: everything is owned locally). Liveness comes from
    the shared store's heartbeat table when one is wired; without a shared
    store the static peer list IS the membership (the in-process test
    harness drives liveness by hand)."""

    def __init__(
        self,
        self_id: str,
        peers: dict[str, str],
        *,
        store=None,
        heartbeat_ttl: float = 10.0,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.self_id = self_id
        self.peers = dict(peers)
        if self_id and self_id not in self.peers:
            self.peers[self_id] = ""
        self.store = store if store is not None and store.shared else None
        self.heartbeat_ttl = max(1.0, heartbeat_ttl)
        self.clock = clock
        # Peers a proxy attempt found dead before their heartbeat expired:
        # rid -> (suspected_at, until). Excluded until `until` passes OR a
        # heartbeat NEWER than the suspicion lands (the peer is provably
        # back — one transient connection failure must not split session
        # ownership for a whole TTL).
        self._suspects: dict[str, tuple[float, float]] = {}
        self._forward_token: str = ""
        self._ring_cache: tuple[tuple[str, ...], list[int], list[str]] | None = None

    # ------------------------------------------------------------- liveness

    def heartbeat(self) -> None:
        """Publish this replica's liveness (and its address, so peers can
        proxy to it without static config)."""
        if self.store is None or not self.self_id:
            return
        self.store.put(
            "replicas",
            self.self_id,
            {"ts": self.clock(), "url": self.peers.get(self.self_id, "")},
        )

    def live_ids(self) -> list[str]:
        """The replica ids currently on the ring: every configured peer
        whose heartbeat is fresh (shared store) minus proxy-suspected
        peers. Self is always a member — a replica that cannot see the
        store must keep serving what it owns. Falls back to the full
        static list when no shared store is wired."""
        now = self.clock()
        suspected = {
            rid: since
            for rid, (since, until) in self._suspects.items()
            if until > now
        }
        if self.store is None:
            ids = [rid for rid in self.peers if rid not in suspected]
        else:
            beats = self.store.items("replicas")
            ids = []
            for rid in self.peers:
                if rid == self.self_id:
                    ids.append(rid)
                    continue
                beat = beats.get(rid)
                ts = beat.get("ts") if isinstance(beat, dict) else None
                fresh = (
                    isinstance(ts, (int, float))
                    and now - ts <= self.heartbeat_ttl
                )
                since = suspected.get(rid)
                if since is not None:
                    # A heartbeat NEWER than the suspicion proves the peer
                    # back: clear it. Otherwise stay excluded.
                    if fresh and ts > since:
                        self._suspects.pop(rid, None)
                    else:
                        continue
                if fresh:
                    ids.append(rid)
        if self.self_id and self.self_id not in ids:
            ids.append(self.self_id)
        return sorted(ids)

    def mark_dead(self, replica_id: str) -> None:
        """A proxy attempt could not reach the peer: drop it from the ring
        for one TTL so its keys rehash NOW instead of after the heartbeat
        ages out."""
        if replica_id == self.self_id:
            return
        now = self.clock()
        self._suspects[replica_id] = (now, now + self.heartbeat_ttl)
        logger.warning(
            "replica %s unreachable; excluding it from the ring for %.0fs "
            "(its sessions rehash to the survivors)",
            replica_id,
            self.heartbeat_ttl,
        )

    def forward_token(self) -> str:
        """The fleet's forwarding secret: minted once into the shared
        store (create-if-absent under the store's lock), readable only by
        replicas. Stamped on proxied requests so the receiving edge can
        tell a PEER's forward (honor the loop guard) from a client
        spoofing the header (ignore it — otherwise any tenant could
        bypass session affinity and split a session across replicas).
        Without a shared store there is no secret channel; returns "" and
        the guard falls back to refusing client-supplied values outright.
        """
        if self.store is None:
            return ""
        token = self._forward_token
        if token:
            return token

        def mint(current):
            if isinstance(current, str) and current:
                return current, current
            import secrets

            fresh = secrets.token_hex(16)
            return fresh, fresh

        token = self.store.mutate("replicas", "_forward_token", mint)
        self._forward_token = token
        return token

    def url_of(self, replica_id: str) -> str:
        url = self.peers.get(replica_id, "")
        if not url and self.store is not None:
            beat = self.store.get("replicas", replica_id)
            if isinstance(beat, dict) and isinstance(beat.get("url"), str):
                url = beat["url"]
        return url

    # ----------------------------------------------------------------- ring

    def _ring(self) -> tuple[list[int], list[str]]:
        members = tuple(self.live_ids())
        cached = self._ring_cache
        if cached is not None and cached[0] == members:
            return cached[1], cached[2]
        points: list[tuple[int, str]] = []
        for rid in members:
            for i in range(_VNODES):
                points.append((_hash(f"{rid}#{i}"), rid))
        points.sort()
        hashes = [p[0] for p in points]
        owners = [p[1] for p in points]
        self._ring_cache = (members, hashes, owners)
        return hashes, owners

    def owner(self, key: str) -> str:
        """The replica id owning ``key`` — the first vnode clockwise from
        the key's hash. Single-member (or empty) rings own everything
        locally."""
        hashes, owners = self._ring()
        if not hashes:
            return self.self_id
        index = bisect_right(hashes, _hash(key)) % len(hashes)
        return owners[index]


class SessionRouter:
    """The edge-side half: decide own-vs-forward for session requests and
    carry out the forwarding (transparent HTTP proxy, or the 307 redirect
    contract when proxying is disabled)."""

    def __init__(
        self,
        ring: ReplicaRing,
        *,
        default_tenant: str = "shared",
        proxy: bool = True,
        proxy_timeout: float = 330.0,
    ) -> None:
        self.ring = ring
        self.default_tenant = default_tenant
        self.proxy_enabled = proxy
        self.proxy_timeout = proxy_timeout
        self._client = None
        self._task: asyncio.Task | None = None
        self.proxied_total = 0
        self.redirected_total = 0

    def route_key(self, tenant: str | None, executor_id: str) -> str:
        return f"{tenant or self.default_tenant}/{executor_id}"

    def owner_of(self, tenant: str | None, executor_id: str) -> str:
        return self.ring.owner(self.route_key(tenant, executor_id))

    def peer_forwarded(self, header_value: str | None) -> bool:
        """Did a PEER replica forward this request (vs a client spoofing
        the header)? Only a value carrying the fleet's shared-store
        secret counts; anything else — including a bare replica id — is
        treated as client noise and the affinity check runs normally."""
        if not header_value:
            return False
        token = self.ring.forward_token()
        if not token:
            return False
        _, _, offered = header_value.partition(":")
        return bool(offered) and offered == token

    def owns(self, tenant: str | None, executor_id: str | None) -> bool:
        """True when this replica should serve the request locally:
        stateless requests always; session requests when the hash ring
        says so (or when no ring peer set is configured at all)."""
        if not executor_id or len(self.ring.peers) <= 1:
            return True
        return self.owner_of(tenant, executor_id) == self.ring.self_id

    # ---------------------------------------------------------- HTTP proxy

    def _http_client(self):
        import httpx

        if self._client is None or self._client.is_closed:
            self._client = httpx.AsyncClient(
                timeout=httpx.Timeout(self.proxy_timeout)
            )
        return self._client

    async def forward(self, request, owner: str):
        """Proxy an aiohttp request to the owner replica (or answer the
        307 redirect when proxying is off). On a connection failure the
        owner is marked dead, the key rehashes, and — when it now lands
        here — the caller serves locally (returns None)."""
        from aiohttp import web

        url = self.ring.url_of(owner)
        if not url:
            # No address for the owner (e.g. membership raced a restart):
            # serve locally rather than fail the request.
            return None
        target = f"{url}{request.path_qs}"
        if not self.proxy_enabled:
            self.redirected_total += 1
            return web.Response(
                status=307,
                headers={
                    "Location": target,
                    "X-Replica-Owner": owner,
                },
            )
        import httpx

        body = await request.read()
        headers = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in ("host", "content-length", "transfer-encoding")
        }
        token = self.ring.forward_token()
        headers["X-Replica-Forwarded-By"] = (
            f"{self.ring.self_id}:{token}" if token else self.ring.self_id
        )
        try:
            client = self._http_client()
            upstream = await client.request(
                request.method, target, content=body, headers=headers
            )
        except (httpx.ConnectError, httpx.ConnectTimeout):
            # The owner is GONE (nothing listening): drop it from the
            # ring so the key rehashes immediately, and hand control back
            # to the caller — it re-evaluates ownership against the
            # shrunken ring (usually: this replica now owns the key and
            # serves it locally).
            self.ring.mark_dead(owner)
            logger.warning(
                "proxy to replica %s failed to connect; ring now %s",
                owner,
                self.ring.live_ids(),
            )
            return None
        except httpx.HTTPError as e:
            # The owner is ALIVE but slow (read timeout mid-request) or
            # the wire broke mid-stream: it may still be RUNNING the
            # request, so neither mark it dead (its live sessions would
            # rehash and split) nor serve locally (the tenant's code
            # would execute twice). Surface the failure; the client
            # retries against a still-owned session.
            logger.warning("proxy to replica %s failed mid-request: %s", owner, e)
            return web.json_response(
                {
                    "error": f"session owner replica {owner!r} did not "
                    f"answer the proxied request ({type(e).__name__}); "
                    "retry",
                },
                status=504,
                headers={"X-Replica-Owner": owner, "Retry-After": "2"},
            )
        self.proxied_total += 1
        passthrough = {
            k: v
            for k, v in upstream.headers.items()
            if k.lower()
            not in ("content-length", "transfer-encoding", "connection")
        }
        passthrough["X-Replica-Owner"] = owner
        return web.Response(
            status=upstream.status_code,
            body=upstream.content,
            headers=passthrough,
        )

    # ------------------------------------------------------------ lifecycle

    def start(self, interval: float = 2.0) -> asyncio.Task | None:
        """Heartbeat loop (shared-store mode only): publish liveness every
        ``interval`` seconds so peers keep this replica on their rings."""
        if self.ring.store is None or self._task is not None:
            return self._task
        self.ring.heartbeat()  # first beat before anyone asks

        async def loop() -> None:
            while True:
                await asyncio.sleep(interval)
                try:
                    self.ring.heartbeat()
                except Exception:  # noqa: BLE001 — liveness must not die
                    logger.exception("replica heartbeat failed")

        self._task = asyncio.get_running_loop().create_task(loop())
        return self._task

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._client is not None and not self._client.is_closed:
            await self._client.aclose()

    def snapshot(self) -> dict:
        """The /statusz replicas block."""
        return {
            "self": self.ring.self_id,
            "peers": sorted(self.ring.peers),
            "live": self.ring.live_ids(),
            "proxy": self.proxy_enabled,
            "proxied_total": self.proxied_total,
            "redirected_total": self.redirected_total,
        }


def make_session_router(config, store=None) -> SessionRouter | None:
    """Build the router from config, or None when no replica set is
    configured (single-replica mode: zero new code on any path)."""
    peers = parse_peers(getattr(config, "replica_peers", "") or "")
    if not peers:
        return None
    self_id = getattr(config, "replica_self", "") or ""
    if not self_id:
        import os
        import socket

        self_id = os.environ.get("POD_NAME") or socket.gethostname()
    if self_id not in peers:
        # Identify self by matching the listen port against a peer addr
        # would be guesswork; be explicit instead.
        logger.warning(
            "APP_REPLICA_SELF=%r is not in APP_REPLICA_PEERS; this replica "
            "will own only keys that hash to it by name",
            self_id,
        )
    ring = ReplicaRing(
        self_id,
        peers,
        store=store,
        heartbeat_ttl=getattr(config, "replica_heartbeat_ttl", 10.0),
    )
    return SessionRouter(
        ring,
        default_tenant=getattr(config, "scheduler_default_tenant", "shared")
        or "shared",
        proxy=bool(getattr(config, "replica_proxy", True)),
    )
