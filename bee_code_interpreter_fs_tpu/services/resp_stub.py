"""A minimal in-repo RESP2 server: the store the contract tests and the
kill-the-store bench leg run RespStateStore against.

This container (and CI) has no Redis server and the project rule is zero
new dependencies, so the stub speaks just enough of the protocol for the
client's command set — PING, SELECT, GET, SET (NX/XX/PX/EX), DEL, MGET,
SADD/SREM/SMEMBERS, EXISTS, FLUSHALL — over a stdlib ThreadingTCPServer.
Expiry is lazy (checked at read/lock time), which is exactly the part of
``SET NX PX`` the client's advisory locks rely on. NOT a Redis: no
persistence, no replication, no pipelining guarantees beyond
one-request-one-reply per connection — a protocol-faithful crash dummy
the bench can SIGKILL and restart to stage a store outage.

Run standalone (the bench spawns this as a subprocess and waits for the
READY line):

    python -m bee_code_interpreter_fs_tpu.services.resp_stub --port 7379

or in-process for tests via ``RespStubServer``.
"""

from __future__ import annotations

import argparse
import socketserver
import threading
import time


class _Store:
    """One shared keyspace (the client's SELECT just switches a db index;
    the stub keeps per-db dicts so SELECT round-trips faithfully)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # db -> key -> (value bytes | set[bytes], expires_at | None)
        self.dbs: dict[int, dict[bytes, tuple[object, float | None]]] = {}

    def db(self, index: int) -> dict:
        return self.dbs.setdefault(index, {})

    def live(self, db: dict, key: bytes):
        entry = db.get(key)
        if entry is None:
            return None
        value, expires = entry
        if expires is not None and time.monotonic() >= expires:
            del db[key]
            return None
        return value


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # noqa: C901 — one branch per command, flat
        store: _Store = self.server.store  # type: ignore[attr-defined]
        db_index = 0
        while True:
            try:
                args = self._read_command()
            except (ConnectionError, OSError, ValueError):
                return
            if args is None:
                return
            if not args:
                continue
            cmd = args[0].upper()
            with store.lock:
                db = store.db(db_index)
                if cmd == b"PING":
                    self._simple("PONG")
                elif cmd == b"SELECT":
                    db_index = int(args[1])
                    self._simple("OK")
                elif cmd == b"FLUSHALL":
                    store.dbs.clear()
                    self._simple("OK")
                elif cmd == b"GET":
                    value = store.live(db, args[1])
                    self._bulk(value if isinstance(value, bytes) else None)
                elif cmd == b"MGET":
                    out = []
                    for key in args[1:]:
                        value = store.live(db, key)
                        out.append(value if isinstance(value, bytes) else None)
                    self._array(out)
                elif cmd == b"SET":
                    self._set(db, store, args)
                elif cmd == b"DEL":
                    removed = 0
                    for key in args[1:]:
                        if store.live(db, key) is not None:
                            del db[key]
                            removed += 1
                    self._int(removed)
                elif cmd == b"EXISTS":
                    self._int(
                        sum(
                            1
                            for key in args[1:]
                            if store.live(db, key) is not None
                        )
                    )
                elif cmd == b"SADD":
                    members = store.live(db, args[1])
                    if not isinstance(members, set):
                        members = set()
                    before = len(members)
                    members.update(args[2:])
                    db[args[1]] = (members, None)
                    self._int(len(members) - before)
                elif cmd == b"SREM":
                    members = store.live(db, args[1])
                    if not isinstance(members, set):
                        self._int(0)
                        continue
                    before = len(members)
                    members.difference_update(args[2:])
                    if members:
                        db[args[1]] = (members, None)
                    else:
                        db.pop(args[1], None)
                    self._int(before - len(members))
                elif cmd == b"SMEMBERS":
                    members = store.live(db, args[1])
                    if not isinstance(members, set):
                        self._array([])
                    else:
                        self._array(sorted(members))
                else:
                    self._error(
                        f"ERR unknown command '{cmd.decode(errors='replace')}'"
                    )

    def _set(self, db: dict, store: _Store, args: list[bytes]) -> None:
        key, value = args[1], args[2]
        nx = xx = False
        expires: float | None = None
        i = 3
        while i < len(args):
            opt = args[i].upper()
            if opt == b"NX":
                nx = True
            elif opt == b"XX":
                xx = True
            elif opt == b"PX":
                i += 1
                expires = time.monotonic() + int(args[i]) / 1000.0
            elif opt == b"EX":
                i += 1
                expires = time.monotonic() + int(args[i])
            else:
                self._error(f"ERR syntax error near {opt!r}")
                return
            i += 1
        exists = store.live(db, key) is not None
        if (nx and exists) or (xx and not exists):
            self._bulk(None)
            return
        db[key] = (value, expires)
        self._simple("OK")

    # ------------------------------------------------------------- protocol

    def _read_command(self) -> list[bytes] | None:
        line = self.rfile.readline()
        if not line:
            return None
        if not line.startswith(b"*"):
            # Inline command (telnet-style) — enough for hand-poking.
            return line.strip().split()
        count = int(line[1:].strip())
        args = []
        for _ in range(count):
            header = self.rfile.readline()
            if not header.startswith(b"$"):
                raise ValueError("malformed bulk header")
            length = int(header[1:].strip())
            data = self.rfile.read(length + 2)
            if len(data) != length + 2:
                raise ConnectionError("truncated bulk body")
            args.append(data[:-2])
        return args

    def _simple(self, text: str) -> None:
        self.wfile.write(f"+{text}\r\n".encode())

    def _error(self, text: str) -> None:
        self.wfile.write(f"-{text}\r\n".encode())

    def _int(self, value: int) -> None:
        self.wfile.write(f":{value}\r\n".encode())

    def _bulk(self, data: bytes | None) -> None:
        if data is None:
            self.wfile.write(b"$-1\r\n")
        else:
            self.wfile.write(b"$%d\r\n%s\r\n" % (len(data), data))

    def _array(self, items: list) -> None:
        self.wfile.write(b"*%d\r\n" % len(items))
        for item in items:
            self._bulk(item)


class RespStubServer:
    """In-process harness: ``with RespStubServer() as url:`` yields a
    ``redis://...`` URL RespStateStore connects to."""

    def __init__(self, port: int = 0) -> None:
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self.server = socketserver.ThreadingTCPServer(
            ("127.0.0.1", port), _Handler
        )
        self.server.daemon_threads = True
        self.server.store = _Store()  # type: ignore[attr-defined]
        self.port = self.server.server_address[1]
        self.url = f"redis://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def start(self) -> "RespStubServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    def __enter__(self) -> str:
        self.start()
        return self.url

    def __exit__(self, *exc) -> None:
        self.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()
    stub = RespStubServer(port=args.port)
    stub.start()
    # The bench subprocess-spawns this and blocks on the READY line.
    print(f"READY {stub.port}", flush=True)
    try:
        stub._thread.join()
    except KeyboardInterrupt:
        stub.stop()


if __name__ == "__main__":
    main()
