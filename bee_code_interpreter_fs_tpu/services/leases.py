"""Per-chip lease fencing: generation tokens and gated re-admission.

The device-health probe (PR 8) can SAY a host is wedged; nothing could
safely ACT on that verdict, because disposal alone does not protect the
replacement — the repo's own outage history (BENCH_r03-r05) is precisely a
stale claim wedging a chip for the next holder: a zombie runner still
holding libtpu, a late-arriving dispatch, a retry racing a dispose. This
module is the fencing primitive that makes dispose-and-replace safe:

- **Generation tokens** — every sandbox spawn mints a monotonic generation
  per lease *scope* (the physical chip-set the sandbox attaches: the
  backend's `lease_scope`, or the chip-count lane by default). The token is
  pushed to the sandbox's executor at attach (`POST /lease`) and stamped on
  every dispatch (`x-lease-token`); an executor holding a NEWER token
  rejects a stale claim with a typed ``409 stale_lease`` before taking any
  lock — a claim minted for a fenced predecessor can never reach the
  successor's device plane, not even to queue behind it.
- **Fencing** — a wedged verdict revokes the host's lease. The control
  plane refuses to dispatch against a revoked lease (typed
  ``StaleLeaseError``, a clean refusal that bills nothing), and the scope's
  next mint is strictly newer, so the successor's executor can tell every
  pre-fence token apart from its own.
- **Gated re-admission** — a fenced scope enters ``recovering``: hosts on
  it (the replacement lands on the same hardware) are probed but serve
  nothing until ``APP_DEVICE_PROBE_READMIT_STREAK`` consecutive clean
  probes; a suspect/wedged relapse resets the streak. Re-admission fires
  ``host_readmitted_total`` and wakes the lanes that were waiting out the
  quarantine.

Scopes deliberately name HARDWARE, not sandboxes: on the local backend
every warm sandbox holds the same physical TPU, so one scope per lane is
exactly the chip-set; on Kubernetes a backend can expose finer scopes via
``lease_scope(chip_count)``. Keying recovery by scope is what makes "the
replacement on the same hardware must re-earn trust" expressible at all.

Event-loop discipline like the scheduler: plain synchronous state driven
from the executor's loop; the clock is injectable so every fencing test
runs with zero sleeps.
"""

from __future__ import annotations

import logging
import time
from collections.abc import Callable
from dataclasses import dataclass, field

from .errors import StateStoreDegradedError
from .state_store import STORE_UNAVAILABLE_ERRORS

logger = logging.getLogger(__name__)

# What a shared-store op can throw when the store is gone: the raw
# transport/file errors (registry wired with a bare store) plus the typed
# degraded refusal (registry wired with the ResilientStateStore wrapper,
# whose FENCED policy fails lease writes closed).
_STORE_DOWN = (StateStoreDegradedError, *STORE_UNAVAILABLE_ERRORS)


@dataclass
class Lease:
    """One sandbox's claim on its scope's chips. Identity object: the
    executor compares `wire_token` strings for equality, the control plane
    checks `revoked` before every dispatch."""

    scope: str
    generation: int
    sandbox_id: str = ""
    revoked: bool = False
    revoke_reason: str = ""

    @property
    def wire_token(self) -> str:
        """The token as it rides the wire (`x-lease-token` header and the
        `POST /lease` body): scope-qualified so a mis-routed dispatch is
        diagnosable from the 409 body alone."""
        return f"{self.scope}:{self.generation}"


@dataclass
class _ScopeRecovery:
    """A fenced scope's re-admission state: how many consecutive clean
    probes its current hardware has shown, out of how many required."""

    streak: int = 0
    need: int = 1
    since: float = 0.0
    relapses: int = 0
    reason: str = ""


class LeaseRegistry:
    """Mints, revokes, and re-admits per-scope generation leases."""

    def __init__(
        self,
        *,
        readmit_streak: int = 3,
        clock: Callable[[], float] = time.monotonic,
        store=None,
        walltime: Callable[[], float] = time.time,
    ) -> None:
        self.readmit_streak = max(1, readmit_streak)
        self.clock = clock
        self.walltime = walltime
        # Shared-state seam (services/state_store.py): with a SHARED store
        # wired, generations mint from one fleet-wide counter per scope
        # (ns="lease_gen") and fences publish a generation FLOOR per scope
        # (ns="lease_fence") — every replica's leases at-or-below the
        # floor are stale, so a host fenced by replica A is refused by
        # replica B's dispatch and pool-pop paths even though B never saw
        # the fence happen. A private store (the default) leaves every
        # path byte-for-byte as before.
        self._store = store if store is not None and store.shared else None
        self._generations: dict[str, int] = {}
        self._recovering: dict[str, _ScopeRecovery] = {}
        # Degraded-mode state (shared store unreachable): last-seen fence
        # floors from successful reads (floors only rise, so a stale value
        # can only under-refuse — and mints fail closed, so nothing new is
        # granted off it), plus floor publishes a fence performed during
        # the outage still owes the fleet (max-merged in, so replay in any
        # order against any peer's concurrent raise is safe).
        self._floor_cache: dict[str, int] = {}
        self._pending_floors: dict[str, int] = {}
        self.fences_total = 0
        self.readmissions_total = 0
        self.degraded_mint_refusals = 0

    # ---------------------------------------------------------------- leases

    def mint(self, scope: str, sandbox_id: str = "") -> Lease:
        """A fresh lease for `scope`, strictly newer than every lease the
        scope ever issued — the monotonicity the executor-side stale check
        rests on. In shared mode the generation comes from the fleet-wide
        counter, so replicas can never mint the same generation twice."""
        if self._store is not None:
            try:
                generation = int(self._store.incr("lease_gen", scope))
            except StateStoreDegradedError:
                self.degraded_mint_refusals += 1
                raise
            except STORE_UNAVAILABLE_ERRORS as e:
                # FAIL CLOSED, always — even when the registry holds a bare
                # store with no resilience wrapper. A partitioned replica
                # minting off its last-seen counter could reissue a
                # generation a peer already granted (or fenced): the one
                # degraded behavior this module can never allow.
                self.degraded_mint_refusals += 1
                raise StateStoreDegradedError(
                    f"lease mint for scope {scope!r} refused: shared "
                    f"generation counter unreachable ({e})",
                    subsystem="leases",
                ) from e
            self._flush_pending_floors()
            self._generations[scope] = max(
                self._generations.get(scope, 0), generation
            )
        else:
            generation = self._generations.get(scope, 0) + 1
            self._generations[scope] = generation
        return Lease(scope=scope, generation=generation, sandbox_id=sandbox_id)

    def current_generation(self, scope: str) -> int:
        return self._generations.get(scope, 0)

    def fence(self, lease: Lease, *, reason: str = "wedged") -> None:
        """Revoke the lease and put its scope into recovering. Idempotent:
        fencing an already-revoked lease changes nothing (the probe may
        re-report a wedge while the dispose is still in flight)."""
        if lease.revoked:
            return
        lease.revoked = True
        lease.revoke_reason = reason
        self.fences_total += 1
        # Burn the generation forward so even a mint racing this fence can
        # never reissue the revoked token.
        self._generations[lease.scope] = max(
            self._generations.get(lease.scope, 0), lease.generation
        )
        self._recovering[lease.scope] = _ScopeRecovery(
            streak=0,
            need=self.readmit_streak,
            since=self.clock(),
            reason=reason,
        )
        if self._store is not None:
            # Publish the generation FLOOR and the recovering record
            # SEPARATELY: the floor is permanent (every lease at-or-below
            # it is stale forever — a peer's pooled host that idled
            # through the whole recovery window must still be refused
            # after re-admission, because its process sat through the
            # wedge), while the recovering record lives only until the
            # clean-probe streak completes (whichever replica's probes
            # complete it).
            def _raise_floor(current):
                floor = lease.generation
                if isinstance(current, (int, float)):
                    floor = max(floor, int(current))
                return floor, None

            def _fence_record(current):
                return (
                    {
                        "reason": reason,
                        "since_wall": self.walltime(),
                        "streak": 0,
                        "need": self.readmit_streak,
                        "relapses": 0,
                    },
                    None,
                )

            try:
                self._store.mutate("lease_floor", lease.scope, _raise_floor)
                self._store.mutate("lease_fence", lease.scope, _fence_record)
            except _STORE_DOWN as e:
                # The LOCAL half already happened (revocation, generation
                # burn, recovering record) — this replica refuses the host
                # either way. What the outage withheld is the FLEET's view:
                # queue the floor raise and replay it on the next healthy
                # store op (floors max-merge, so late replay against a
                # peer's newer floor is a no-op). Until then a peer may
                # keep serving this scope off pre-fence leases — the same
                # exposure as the fence simply racing the outage.
                self._pending_floors[lease.scope] = max(
                    self._pending_floors.get(lease.scope, 0),
                    lease.generation,
                )
                logger.warning(
                    "lease fence for scope=%s could not publish to the "
                    "shared store (%s): floor %d queued for replay on "
                    "reconnect",
                    lease.scope,
                    e,
                    lease.generation,
                )
        logger.warning(
            "lease fenced: scope=%s generation=%d sandbox=%s (%s); "
            "re-admission needs %d clean probes",
            lease.scope,
            lease.generation,
            lease.sandbox_id,
            reason,
            self.readmit_streak,
        )

    @staticmethod
    def revoked(lease: Lease | None) -> bool:
        return lease is not None and lease.revoked

    def stale(self, lease: Lease | None) -> bool:
        """Is this lease no longer honorable? Locally revoked, or (shared
        mode) at-or-below the scope's published fence floor — the check
        that makes "a host fenced by replica A is never granted by
        replica B" true: B's pool-pop and dispatch paths consult this
        even though B never observed A's fence."""
        if lease is None:
            return False
        if lease.revoked:
            return True
        if self._store is not None:
            # A fence this replica performed during an outage refuses its
            # scope immediately, before the floor ever lands remotely.
            pending = self._pending_floors.get(lease.scope)
            if pending is not None and lease.generation <= pending:
                return True
            # Deliberately UNCACHED (unlike the breaker's 0.25s remote
            # cache): this read is the only thing standing between a
            # peer's fence and this replica granting the fenced host — a
            # freshness window here would be a grant-a-wedged-host window.
            # WAL readers never block on writers, so the cost is one
            # ~tens-of-µs point read per dispatch/pool-candidate.
            try:
                floor = self._store.get("lease_floor", lease.scope)
            except _STORE_DOWN:
                # Store gone: serve off the last floor a healthy read saw.
                # Floors only rise, so the cache can only UNDER-refuse —
                # and the thing it could miss (a peer's fence during the
                # outage) cannot strand a wedge on THIS replica: mints are
                # refused store-down, so no new local lease lands on the
                # scope, and existing leases predate the peer's fence by
                # construction.
                floor = self._floor_cache.get(lease.scope)
            else:
                if isinstance(floor, (int, float)):
                    self._floor_cache[lease.scope] = int(floor)
                self._flush_pending_floors()
            if isinstance(floor, (int, float)) and lease.generation <= floor:
                # The floor survives re-admission on purpose: the scope's
                # HARDWARE re-earned trust, but a pre-fence lease names a
                # sandbox process that sat through the wedge — only
                # post-fence generations serve.
                return True
        return False

    def _flush_pending_floors(self) -> None:
        """Replay floor raises a store-down fence left owing, on the first
        healthy store op that notices them. Max-merge makes replay order
        irrelevant; a relapse mid-flush just leaves the remainder queued."""
        if not self._pending_floors:
            return
        for scope, generation in list(self._pending_floors.items()):

            def _raise_floor(current, generation=generation):
                floor = generation
                if isinstance(current, (int, float)):
                    floor = max(floor, int(current))
                return floor, None

            try:
                self._store.mutate("lease_floor", scope, _raise_floor)
            except _STORE_DOWN:
                return
            self._pending_floors.pop(scope, None)
            self._floor_cache[scope] = max(
                self._floor_cache.get(scope, 0), generation
            )
            logger.info(
                "replayed queued fence floor: scope=%s floor=%d",
                scope,
                generation,
            )

    # ------------------------------------------------------------ recovering

    def recovering(self, scope: str) -> bool:
        if self._store is not None:
            # Shared mode: the store is authoritative. A local mirror
            # whose shared record is gone means a PEER's probes completed
            # the streak — drop the mirror so this replica's gates open
            # too (its lanes re-evaluate on the next sweep kick).
            try:
                record = self._store.get("lease_fence", scope)
            except _STORE_DOWN:
                return scope in self._recovering
            if record is not None:
                return True
            if getattr(self._store, "degraded", False):
                # A degraded wrapper answers reads from its last-known
                # cache: an absence there is NOT evidence a peer finished
                # the streak — keep the local mirror authoritative until
                # a healthy read says otherwise.
                return scope in self._recovering
            self._recovering.pop(scope, None)
            return False
        return scope in self._recovering

    def recovery_progress(self, scope: str) -> tuple[int, int]:
        """(clean streak so far, streak required); (0, 0) when the scope is
        not recovering."""
        state = self._recovering.get(scope)
        if state is None:
            return 0, 0
        return state.streak, state.need

    def note_probe(self, scope: str, *, clean: bool) -> bool:
        """One probe verdict for a recovering scope's hardware. Clean
        (healthy/busy) probes advance the streak; a suspect/wedged relapse
        resets it — the fenced hardware must prove a CONSECUTIVE run of
        good behavior, not a lucky sample. Returns True exactly once, when
        the streak completes and the scope re-admits."""
        state = self._recovering.get(scope)
        if self._store is not None:
            # Shared mode: the store's record is AUTHORITATIVE, and the
            # whole read-advance-write runs inside ONE store mutation —
            # both replicas' probes advance a single streak over the same
            # hardware, and a peer's concurrent relapse can never be lost
            # to a get-then-write interleave (the scope must prove a
            # CONSECUTIVE clean run, fleet-wide).
            def step(current):
                if current is None:
                    return None, ("absent", None)
                record = dict(current) if isinstance(current, dict) else {}
                if not clean:
                    record["streak"] = 0
                    record["relapses"] = int(record.get("relapses", 0) or 0) + 1
                    return record, ("relapse", record)
                streak = int(record.get("streak", 0) or 0) + 1
                need = int(record.get("need", self.readmit_streak) or 1)
                if streak >= need:
                    return None, ("readmit", record)
                record["streak"] = streak
                return record, ("advance", record)

            try:
                verdict, record = self._store.mutate(
                    "lease_fence", scope, step
                )
            except _STORE_DOWN:
                # Store down: keep the consecutive-streak contract alive on
                # the LOCAL mirror so this replica's own probes still gate
                # its own re-admission. On reconnect the shared record —
                # still standing with its pre-outage streak — is
                # authoritative again, so the fleet may ask the hardware
                # for a few extra clean probes. Conservative by design:
                # degraded mode must never re-admit EARLIER than the
                # healthy path would.
                return self._note_probe_local(scope, clean)
            if verdict == "absent":
                if state is not None:
                    # A peer's probe completed the streak: mirror the
                    # re-admission here so this replica settles its lanes.
                    del self._recovering[scope]
                    self.readmissions_total += 1
                    logger.info(
                        "lease scope %s re-admitted (completed by a peer "
                        "replica's probes)",
                        scope,
                    )
                    return True
                return False
            # Mirror the post-step record locally (statusz/progress reads).
            if state is None:
                state = _ScopeRecovery(
                    since=self.clock(),
                    reason=str(record.get("reason", "") or ""),
                )
                self._recovering[scope] = state
            state.need = int(record.get("need", self.readmit_streak) or 1)
            state.relapses = int(record.get("relapses", 0) or 0)
            if verdict == "relapse":
                if state.streak:
                    logger.info(
                        "lease scope %s relapsed mid-recovery "
                        "(streak was %d/%d)",
                        scope,
                        state.streak,
                        state.need,
                    )
                state.streak = 0
                return False
            if verdict == "advance":
                state.streak = int(record.get("streak", 0) or 0)
                return False
            # verdict == "readmit": the mutation already deleted the
            # shared record — finish locally.
            del self._recovering[scope]
            self.readmissions_total += 1
            logger.info(
                "lease scope %s re-admitted after %d clean probes "
                "(%.1fs in recovery, %d relapse(s))",
                scope,
                state.need,
                max(0.0, self.clock() - state.since),
                state.relapses,
            )
            return True
        # Private-store path from here: today's single-process semantics.
        return self._note_probe_local(scope, clean)

    def _note_probe_local(self, scope: str, clean: bool) -> bool:
        """The registry-local streak step: the private-store semantics,
        doubling as the degraded-mode fallback while a shared store is
        unreachable."""
        state = self._recovering.get(scope)
        if state is None:
            return False
        if not clean:
            if state.streak:
                logger.info(
                    "lease scope %s relapsed mid-recovery (streak was %d/%d)",
                    scope,
                    state.streak,
                    state.need,
                )
            state.streak = 0
            state.relapses += 1
            return False
        state.streak += 1
        if state.streak < state.need:
            return False
        del self._recovering[scope]
        self.readmissions_total += 1
        logger.info(
            "lease scope %s re-admitted after %d clean probes "
            "(%.1fs in recovery, %d relapse(s))",
            scope,
            state.need,
            max(0.0, self.clock() - state.since),
            state.relapses,
        )
        return True

    # -------------------------------------------------------------- surfaces

    def snapshot(self) -> dict:
        """The /statusz recovery block's lease half: per-scope generations
        and any in-flight re-admission streaks."""
        now = self.clock()
        recovering = {
            scope: {
                "streak": state.streak,
                "need": state.need,
                "relapses": state.relapses,
                "for_s": round(max(0.0, now - state.since), 3),
                "reason": state.reason,
            }
            for scope, state in sorted(self._recovering.items())
        }
        if self._store is not None:
            # Peers' standing fences surface here too: an operator reading
            # ANY replica's /statusz sees every scope the fleet is
            # quarantining, not just the ones this process fenced.
            wall = self.walltime()
            try:
                fences = self._store.items("lease_fence")
            except _STORE_DOWN:
                fences = {}  # statusz stays serveable through an outage
            for scope, record in sorted(fences.items()):
                if scope in recovering or not isinstance(record, dict):
                    continue
                since = record.get("since_wall")
                recovering[scope] = {
                    "streak": int(record.get("streak", 0) or 0),
                    "need": int(record.get("need", self.readmit_streak) or 1),
                    "relapses": int(record.get("relapses", 0) or 0),
                    "for_s": round(
                        max(0.0, wall - since)
                        if isinstance(since, (int, float))
                        else 0.0,
                        3,
                    ),
                    "reason": str(record.get("reason", "") or ""),
                }
        return {
            "readmit_streak": self.readmit_streak,
            "fences_total": self.fences_total,
            "readmissions_total": self.readmissions_total,
            "degraded_mint_refusals": self.degraded_mint_refusals,
            "pending_fence_floors": dict(sorted(self._pending_floors.items())),
            "generations": dict(sorted(self._generations.items())),
            "recovering": recovering,
        }
