"""Per-chip lease fencing: generation tokens and gated re-admission.

The device-health probe (PR 8) can SAY a host is wedged; nothing could
safely ACT on that verdict, because disposal alone does not protect the
replacement — the repo's own outage history (BENCH_r03-r05) is precisely a
stale claim wedging a chip for the next holder: a zombie runner still
holding libtpu, a late-arriving dispatch, a retry racing a dispose. This
module is the fencing primitive that makes dispose-and-replace safe:

- **Generation tokens** — every sandbox spawn mints a monotonic generation
  per lease *scope* (the physical chip-set the sandbox attaches: the
  backend's `lease_scope`, or the chip-count lane by default). The token is
  pushed to the sandbox's executor at attach (`POST /lease`) and stamped on
  every dispatch (`x-lease-token`); an executor holding a NEWER token
  rejects a stale claim with a typed ``409 stale_lease`` before taking any
  lock — a claim minted for a fenced predecessor can never reach the
  successor's device plane, not even to queue behind it.
- **Fencing** — a wedged verdict revokes the host's lease. The control
  plane refuses to dispatch against a revoked lease (typed
  ``StaleLeaseError``, a clean refusal that bills nothing), and the scope's
  next mint is strictly newer, so the successor's executor can tell every
  pre-fence token apart from its own.
- **Gated re-admission** — a fenced scope enters ``recovering``: hosts on
  it (the replacement lands on the same hardware) are probed but serve
  nothing until ``APP_DEVICE_PROBE_READMIT_STREAK`` consecutive clean
  probes; a suspect/wedged relapse resets the streak. Re-admission fires
  ``host_readmitted_total`` and wakes the lanes that were waiting out the
  quarantine.

Scopes deliberately name HARDWARE, not sandboxes: on the local backend
every warm sandbox holds the same physical TPU, so one scope per lane is
exactly the chip-set; on Kubernetes a backend can expose finer scopes via
``lease_scope(chip_count)``. Keying recovery by scope is what makes "the
replacement on the same hardware must re-earn trust" expressible at all.

Event-loop discipline like the scheduler: plain synchronous state driven
from the executor's loop; the clock is injectable so every fencing test
runs with zero sleeps.
"""

from __future__ import annotations

import logging
import time
from collections.abc import Callable
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


@dataclass
class Lease:
    """One sandbox's claim on its scope's chips. Identity object: the
    executor compares `wire_token` strings for equality, the control plane
    checks `revoked` before every dispatch."""

    scope: str
    generation: int
    sandbox_id: str = ""
    revoked: bool = False
    revoke_reason: str = ""

    @property
    def wire_token(self) -> str:
        """The token as it rides the wire (`x-lease-token` header and the
        `POST /lease` body): scope-qualified so a mis-routed dispatch is
        diagnosable from the 409 body alone."""
        return f"{self.scope}:{self.generation}"


@dataclass
class _ScopeRecovery:
    """A fenced scope's re-admission state: how many consecutive clean
    probes its current hardware has shown, out of how many required."""

    streak: int = 0
    need: int = 1
    since: float = 0.0
    relapses: int = 0
    reason: str = ""


class LeaseRegistry:
    """Mints, revokes, and re-admits per-scope generation leases."""

    def __init__(
        self,
        *,
        readmit_streak: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.readmit_streak = max(1, readmit_streak)
        self.clock = clock
        self._generations: dict[str, int] = {}
        self._recovering: dict[str, _ScopeRecovery] = {}
        self.fences_total = 0
        self.readmissions_total = 0

    # ---------------------------------------------------------------- leases

    def mint(self, scope: str, sandbox_id: str = "") -> Lease:
        """A fresh lease for `scope`, strictly newer than every lease the
        scope ever issued — the monotonicity the executor-side stale check
        rests on."""
        generation = self._generations.get(scope, 0) + 1
        self._generations[scope] = generation
        return Lease(scope=scope, generation=generation, sandbox_id=sandbox_id)

    def current_generation(self, scope: str) -> int:
        return self._generations.get(scope, 0)

    def fence(self, lease: Lease, *, reason: str = "wedged") -> None:
        """Revoke the lease and put its scope into recovering. Idempotent:
        fencing an already-revoked lease changes nothing (the probe may
        re-report a wedge while the dispose is still in flight)."""
        if lease.revoked:
            return
        lease.revoked = True
        lease.revoke_reason = reason
        self.fences_total += 1
        # Burn the generation forward so even a mint racing this fence can
        # never reissue the revoked token.
        self._generations[lease.scope] = max(
            self._generations.get(lease.scope, 0), lease.generation
        )
        self._recovering[lease.scope] = _ScopeRecovery(
            streak=0,
            need=self.readmit_streak,
            since=self.clock(),
            reason=reason,
        )
        logger.warning(
            "lease fenced: scope=%s generation=%d sandbox=%s (%s); "
            "re-admission needs %d clean probes",
            lease.scope,
            lease.generation,
            lease.sandbox_id,
            reason,
            self.readmit_streak,
        )

    @staticmethod
    def revoked(lease: Lease | None) -> bool:
        return lease is not None and lease.revoked

    # ------------------------------------------------------------ recovering

    def recovering(self, scope: str) -> bool:
        return scope in self._recovering

    def recovery_progress(self, scope: str) -> tuple[int, int]:
        """(clean streak so far, streak required); (0, 0) when the scope is
        not recovering."""
        state = self._recovering.get(scope)
        if state is None:
            return 0, 0
        return state.streak, state.need

    def note_probe(self, scope: str, *, clean: bool) -> bool:
        """One probe verdict for a recovering scope's hardware. Clean
        (healthy/busy) probes advance the streak; a suspect/wedged relapse
        resets it — the fenced hardware must prove a CONSECUTIVE run of
        good behavior, not a lucky sample. Returns True exactly once, when
        the streak completes and the scope re-admits."""
        state = self._recovering.get(scope)
        if state is None:
            return False
        if not clean:
            if state.streak:
                logger.info(
                    "lease scope %s relapsed mid-recovery (streak was %d/%d)",
                    scope,
                    state.streak,
                    state.need,
                )
            state.streak = 0
            state.relapses += 1
            return False
        state.streak += 1
        if state.streak < state.need:
            return False
        del self._recovering[scope]
        self.readmissions_total += 1
        logger.info(
            "lease scope %s re-admitted after %d clean probes "
            "(%.1fs in recovery, %d relapse(s))",
            scope,
            state.need,
            max(0.0, self.clock() - state.since),
            state.relapses,
        )
        return True

    # -------------------------------------------------------------- surfaces

    def snapshot(self) -> dict:
        """The /statusz recovery block's lease half: per-scope generations
        and any in-flight re-admission streaks."""
        now = self.clock()
        return {
            "readmit_streak": self.readmit_streak,
            "fences_total": self.fences_total,
            "readmissions_total": self.readmissions_total,
            "generations": dict(sorted(self._generations.items())),
            "recovering": {
                scope: {
                    "streak": state.streak,
                    "need": state.need,
                    "relapses": state.relapses,
                    "for_s": round(max(0.0, now - state.since), 3),
                    "reason": state.reason,
                }
                for scope, state in sorted(self._recovering.items())
            },
        }
