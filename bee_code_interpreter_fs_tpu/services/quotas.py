"""Per-tenant quota enforcement and abuse control — the layer that READS
the PR 9 usage ledger and acts on it at admission, before the scheduler
ever enqueues a request.

The metering plane (services/usage.py) made every tenant's consumption
attributable; the scheduler (PR 2) made CONTENTION fair. Neither bounds
what one tenant may consume in absolute terms: fair-share still lets a
single tenant monopolize the fleet for as long as it keeps queueing, and
a violation-storm tenant burns a sandbox (spawn, watchdog kill, dispose,
refill) per rejected attempt. This module is the admission-control
discipline beneath the scheduler — what "can be run as a service for
millions of users" means once the metrics labels already have tenants in
them:

- **Sliding-window chip-second budgets** — a tenant's consumption over the
  last ``window_seconds`` (computed from the ledger's monotonic
  ``chip_seconds`` counter against a ring of timestamped samples) may not
  exceed its budget. Over budget → denied at the door with a Retry-After
  computed from the window's actual refill point (the moment enough old
  consumption ages out), not a guess.
- **Request-rate and concurrent-grant caps** — admitted requests per
  window and in-flight requests, bounded per tenant before any queueing.
- **Violation quotas with quarantine** — typed limit violations (PR 5's
  oom/disk_quota/nproc/cpu_time/output_cap kinds, from the ledger's
  violations-by-kind counters) over the window cross a threshold → the
  tenant is QUARANTINED: shed at admission with a distinct reason, zero
  sandboxes consumed per rejected attempt. Quarantine durations grow
  exponentially per episode (base * 2^(n-1), capped) and the offender
  level decays one step per clean decay-interval after release.
- **Policy** — a default policy from config knobs plus per-tenant
  overrides in an ``APP_QUOTA_POLICY_FILE`` JSON, hot-reloaded on mtime
  change (a malformed rewrite keeps the last good policy — quota
  enforcement must never fail open because an operator fat-fingered JSON).

Restart semantics: windows restore from the ledger's own journal
(``UsageLedger.iter_persisted``) — each journal line is a timestamped
cumulative counter sample, so the ring rebuilds to within one flush
interval of where a SIGKILL'd control plane left it. An offender cannot
earn a fresh budget by crashing the service.

Tenant identity: window state is keyed by the LEDGER's row label
(``UsageLedger.peek`` — the same ``_overflow`` cap rule), so enforcement
and billing can never disagree about where a tenant's consumption lives,
and minting fresh tenant names past the cap lands every minted name on
one shared ``_overflow`` budget — name-minting is a self-defeating
evasion, and metric-label cardinality stays bounded by construction.

``APP_QUOTAS_ENABLED=0`` is the kill switch: no admission checks, no
``/quotas`` surface, no quota fields in ``Result.phases``, no ``quota_*``
metric samples — pre-quota behavior byte-for-byte.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field, replace

from ..utils import tracing
from .errors import QuotaExceededError, StateStoreDegradedError
from .state_store import STORE_UNAVAILABLE_ERRORS

logger = logging.getLogger(__name__)

# Everything a fleet-window store op may throw when the shared store is
# unreachable: raw transport errors (bare store) or the wrapper's typed
# refusal. Quota accrual fails OPEN past either — enforcement drops to
# replica-local windows (the PR 15 N-replica bound) rather than denying
# traffic because the bookkeeper is down.
_STORE_DOWN = (StateStoreDegradedError, *STORE_UNAVAILABLE_ERRORS)

# Denial reasons, a closed set (they label quota_denials_total and ride the
# wire as x-quota-reason): membership is contract for dashboards and tests.
DENIAL_REASONS = (
    "chip_seconds",
    "hbm_byte_seconds",
    "burst_credits",
    "predicted_overrun",
    "request_rate",
    "concurrency",
    "quarantined",
)

# Policy keys a file override may set (mirrors the APP_QUOTA_* knobs).
_POLICY_KEYS = (
    "chip_seconds_per_window",
    "hbm_byte_seconds_per_window",
    "burst_credits",
    "refill_per_second",
    "window_seconds",
    "requests_per_window",
    "max_concurrent",
    "violations_per_window",
    "quarantine_base_seconds",
    "quarantine_max_seconds",
    "quarantine_decay_seconds",
)

# Window-sample ring bound per tenant: granularity self-adjusts (samples
# closer together than window/_RING_MAX coalesce), so the ring covers the
# whole window at bounded memory whatever the request rate.
_RING_MAX = 128


@dataclass(frozen=True)
class QuotaPolicy:
    """One tenant's effective policy. 0 = that cap is off (the config
    defaults are all-zero, so an unconfigured deployment enforces
    nothing and behaves exactly as before this subsystem)."""

    chip_seconds_per_window: float = 0.0
    # Device-memory budget over the same window: byte-seconds of peak HBM
    # integrated over device-op wall (the ledger's hbm_byte_seconds
    # counter, PR 14) — a memory hog is bounded like a compute hog.
    hbm_byte_seconds_per_window: float = 0.0
    # Burst-credit smoothing (opt-in, BOTH knobs > 0 to engage): a token
    # bucket of chip-seconds beside the hard window — bursty tenants draw
    # down credit and smooth out at refill_per_second instead of slamming
    # into the window edge.
    burst_credits: float = 0.0
    refill_per_second: float = 0.0
    window_seconds: float = 3600.0
    requests_per_window: int = 0
    max_concurrent: int = 0
    violations_per_window: int = 0
    quarantine_base_seconds: float = 30.0
    quarantine_max_seconds: float = 3600.0
    quarantine_decay_seconds: float = 300.0

    def burst_mode(self) -> bool:
        return self.burst_credits > 0 and self.refill_per_second > 0

    def enforces_anything(self) -> bool:
        return (
            self.chip_seconds_per_window > 0
            or self.hbm_byte_seconds_per_window > 0
            or self.burst_mode()
            or self.requests_per_window > 0
            or self.max_concurrent > 0
            or self.violations_per_window > 0
        )


def _policy_from_mapping(
    base: QuotaPolicy, raw: dict, *, source: str
) -> QuotaPolicy:
    """Layer a policy-file mapping over `base`. Raises ValueError on
    malformed entries — the caller decides whether that fails boot (config
    defaults) or keeps the last good policy (hot reload)."""
    if not isinstance(raw, dict):
        raise ValueError(f"{source} must be an object of policy values")
    updates: dict[str, float | int] = {}
    for key, value in raw.items():
        if key not in _POLICY_KEYS:
            raise ValueError(
                f"unknown {source} key {key!r} (want one of "
                f"{sorted(_POLICY_KEYS)})"
            )
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValueError(f"{source}.{key} must be a number")
        if not math.isfinite(value) or value < 0:
            raise ValueError(f"{source}.{key} must be a finite number >= 0")
        updates[key] = (
            int(value)
            if key in ("requests_per_window", "max_concurrent",
                       "violations_per_window")
            else float(value)
        )
    policy = replace(base, **updates)
    if policy.window_seconds <= 0 and policy.enforces_anything():
        raise ValueError(f"{source}.window_seconds must be > 0")
    return policy


@dataclass
class QuotaVerdict:
    """An ADMITTED request's quota context: what the executor needs to
    release the concurrency slot at exit and to stamp the success-path
    `quota` block into Result.phases (so well-behaved clients can pace
    themselves instead of discovering the budget via 429)."""

    tenant: str
    remaining_chip_seconds: float | None = None
    limit_chip_seconds: float | None = None
    window_seconds: float | None = None
    burst_credits_remaining: float | None = None
    released: bool = False

    def phases_block(self) -> dict | None:
        """THE shape of the Result.phases `quota` block (the executor
        refreshes `remaining_chip_seconds` post-run, then calls this —
        one definition, so wire shape and admission shape cannot
        drift)."""
        if self.limit_chip_seconds is None and self.burst_credits_remaining is None:
            return None
        block: dict = {}
        if self.limit_chip_seconds is not None:
            block = {
                "remaining_chip_seconds": round(
                    self.remaining_chip_seconds or 0.0, 6
                ),
                "limit_chip_seconds": round(self.limit_chip_seconds, 6),
                "window_seconds": round(self.window_seconds or 0.0, 3),
            }
        if self.burst_credits_remaining is not None:
            block["burst_credits_remaining"] = round(
                max(0.0, self.burst_credits_remaining), 6
            )
        return block


class _TenantWindow:
    """One ledger row's sliding-window state: a bounded ring of
    (ts, chip_seconds_cum, violations_cum, hbm_byte_seconds_cum) samples,
    admission timestamps for the rate cap, the in-flight count, the
    burst-credit bucket, and the offender ladder."""

    # Sample-tuple value indexes (budget_refill_at generalizes over them).
    CHIP = 1
    VIOLATIONS = 2
    HBM = 3

    __slots__ = (
        "samples",
        "admits",
        "in_flight",
        "offender_level",
        "quarantined_until",
        "violation_floor",
        "denials",
        "quarantines",
        "last_denial_log",
        "burst_level",
        "burst_refill_ts",
        "burst_anchor",
    )

    def __init__(self) -> None:
        self.samples: deque[tuple[float, float, float, float]] = deque()
        self.admits: deque[float] = deque()
        self.in_flight = 0
        # The exponential ladder: each quarantine episode raises the level
        # (longer next sentence); clean time after release decays it.
        self.offender_level = 0
        self.quarantined_until = 0.0
        # Violations already "spent" by a previous quarantine sentence:
        # the window ring still holds them, but re-counting them at
        # release would re-quarantine instantly and the sentence would
        # degenerate to "locked out until the window drains".
        self.violation_floor = 0.0
        self.denials = 0
        self.quarantines = 0
        self.last_denial_log = 0.0
        # Burst-credit bucket (None until the burst policy first touches
        # this tenant): current credit level, the last refill instant, and
        # the cumulative chip-second value the bucket last drained to.
        self.burst_level: float | None = None
        self.burst_refill_ts = 0.0
        self.burst_anchor = 0.0

    def observe(
        self,
        now: float,
        chip_cum: float,
        violations_cum: float,
        window: float,
        hbm_cum: float = 0.0,
    ) -> None:
        """Record a cumulative sample and prune the ring. The newest sample
        at-or-before the window start is KEPT — it is the baseline
        used_in_window subtracts from."""
        granularity = max(window / _RING_MAX, 0.05)
        if len(self.samples) >= 2 and now - self.samples[-1][0] < granularity:
            # Never coalesce into the OLDEST sample: it is the window
            # baseline, and folding newer consumption into it would zero
            # the very usage the window exists to count.
            # Coalesce: keep the OLDER timestamp with the NEWER cumulative
            # value (conservative — consumption attributes as early as the
            # ring can place it, so a burst can never dodge the window by
            # landing between samples).
            ts = self.samples[-1][0]
            self.samples[-1] = (ts, chip_cum, violations_cum, hbm_cum)
        else:
            self.samples.append((now, chip_cum, violations_cum, hbm_cum))
        window_start = now - window
        while (
            len(self.samples) > 1 and self.samples[1][0] <= window_start
        ) or len(self.samples) > _RING_MAX:
            self.samples.popleft()

    def _baseline(self, now: float, window: float) -> tuple[float, float, float]:
        """Cumulative (chip, violations, hbm) at the window start: the
        newest sample at-or-before it, else the oldest sample (the
        tenant's whole recorded history is inside the window)."""
        window_start = now - window
        base = self.samples[0]
        for sample in self.samples:
            if sample[0] <= window_start:
                base = sample
            else:
                break
        return base[self.CHIP], base[self.VIOLATIONS], base[self.HBM]

    def used_chip_seconds(self, now: float, window: float) -> float:
        if not self.samples:
            return 0.0
        chip_base, _, _ = self._baseline(now, window)
        return max(0.0, self.samples[-1][self.CHIP] - chip_base)

    def used_hbm_byte_seconds(self, now: float, window: float) -> float:
        if not self.samples:
            return 0.0
        _, _, hbm_base = self._baseline(now, window)
        return max(0.0, self.samples[-1][self.HBM] - hbm_base)

    def violations_in_window(self, now: float, window: float) -> float:
        if not self.samples:
            return 0.0
        _, violation_base, _ = self._baseline(now, window)
        return max(
            0.0,
            self.samples[-1][self.VIOLATIONS]
            - max(violation_base, self.violation_floor),
        )

    def budget_refill_at(
        self, now: float, window: float, budget: float, index: int = CHIP
    ) -> float:
        """The earliest time the windowed consumption of sample value
        `index` (chip-seconds by default, HBM byte-seconds for the memory
        budget) can drop to the budget: the first sample whose age-out
        leaves consumption <= budget. The Retry-After contract: a client
        that waits this long is not structurally denied again for the
        same window contents."""
        if not self.samples:
            return now
        value_now = self.samples[-1][index]
        for sample in self.samples:
            if value_now - sample[index] <= budget:
                return sample[0] + window
        # Even the newest sample's baseline leaves it over budget (one
        # giant burst): the whole burst must age out.
        return self.samples[-1][0] + window

    def prune_admits(self, now: float, window: float) -> None:
        while self.admits and self.admits[0] <= now - window:
            self.admits.popleft()


class _FleetWindows:
    """Fleet-coherent accrual over the shared store — the piece that
    closes PR 15's documented N× bound (each of N replicas granting a
    tenant its FULL window budget).

    Mechanism: per (tenant, kind) the window is a ring of coarse time
    buckets in the store (ns=``quota_win``, key ``{label}|{kind}|{bucket}``,
    bucket = wall // granularity, granularity = window/8). Accrual
    publishes as pure ``incr`` deltas — commutative, so N replicas
    publishing concurrently never lose updates AND the degraded-mode
    journal can replay them in any order after an outage. Admission then
    checks ``max(local, fleet)``: max, not sum, because this replica's own
    deltas are inside both views — the fleet view can only TIGHTEN the
    local bound, never loosen it, and a store outage degrades exactly to
    the local bound.

    Kinds: ``chip`` (chip-seconds), ``hbm`` (HBM byte-seconds), ``req``
    (admitted requests). Quarantine/violation state and the concurrency
    cap stay deliberately per-replica: quarantine is an ESCALATING
    sentence keyed to local observation ordering (merging episode ladders
    across replicas would double-sentence a single storm), and in-flight
    counts churn far too fast for a 0.25s-coherent store view — both are
    documented in README's degraded-mode matrix.

    Coarseness: the bucketed window can over-count by up to one granule
    versus the exact local ring — the fleet bound is conservative
    (over-strict), never permissive.
    """

    NS = "quota_win"
    BUCKETS = 8
    # Store reads are throttled: admission happens per request, the items()
    # scan is one cross-replica read — a 0.25s-stale fleet view is the same
    # freshness class as the breaker's remote-verdict cache.
    READ_TTL = 0.25

    def __init__(self, store, *, walltime=time.time) -> None:
        self.store = store
        self.walltime = walltime
        # (label, kind) -> last-published cumulative counter value.
        self._anchors: dict[tuple[str, str], float] = {}
        self._cache: dict = {}
        self._cache_at = -1e9
        self.publish_errors = 0

    @staticmethod
    def _key(label: str, kind: str, bucket: int) -> str:
        return f"{label}|{kind}|{bucket}"

    def _gran(self, window: float) -> float:
        return max(1.0, float(window) / self.BUCKETS)

    def publish_cum(
        self, label: str, kind: str, cumulative: float, window: float
    ) -> None:
        """Publish a MONOTONIC cumulative counter (the ledger's
        chip-second/HBM rows) as the delta since its last sight. The first
        sight only anchors — history predating this process's view already
        belongs to whoever published it."""
        anchor = self._anchors.get((label, kind))
        self._anchors[(label, kind)] = cumulative
        if anchor is None or cumulative <= anchor:
            return
        self.add(label, kind, cumulative - anchor, window)

    def add(self, label: str, kind: str, delta: float, window: float) -> None:
        """One accrual increment into the current bucket (+ lazy
        retirement of the bucket that aged past every window view)."""
        gran = self._gran(window)
        bucket = int(self.walltime() // gran)
        try:
            self.store.incr(self.NS, self._key(label, kind, bucket), delta)
            self.store.delete(
                self.NS, self._key(label, kind, bucket - self.BUCKETS - 2)
            )
        except _STORE_DOWN:
            # Fail open: local enforcement carries on; the delta is lost
            # to the FLEET view only when the store is bare (the resilient
            # wrapper journals incr deltas and replays them on reconnect).
            self.publish_errors += 1

    def _items(self) -> dict:
        now = self.walltime()
        if now - self._cache_at <= self.READ_TTL:
            return self._cache
        self._cache_at = now  # set first: a dead store isn't re-read hot
        try:
            self._cache = self.store.items(self.NS)
        except _STORE_DOWN:
            self.publish_errors += 1
            self._cache = {}
        return self._cache

    def _buckets(
        self, label: str, kind: str, window: float
    ) -> list[tuple[int, float]]:
        gran = self._gran(window)
        floor = int((self.walltime() - window) // gran) + 1
        prefix = f"{label}|{kind}|"
        out = []
        for key, value in self._items().items():
            if not key.startswith(prefix):
                continue
            tail = key[len(prefix):]
            if not isinstance(value, (int, float)):
                continue
            try:
                bucket = int(tail)
            except ValueError:
                continue
            if bucket >= floor:
                out.append((bucket, float(value)))
        out.sort()
        return out

    def used(self, label: str, kind: str, window: float) -> float:
        """Fleet-wide consumption of `kind` inside the window (bucketed:
        conservative by up to one granule)."""
        return sum(v for _, v in self._buckets(label, kind, window))

    def refill_in(
        self, label: str, kind: str, window: float, budget: float
    ) -> float:
        """Seconds until enough fleet buckets age out that consumption
        fits the budget — the Retry-After contract, fleet edition."""
        buckets = self._buckets(label, kind, window)
        excess = sum(v for _, v in buckets) - budget
        if excess <= 0:
            return 0.0
        gran = self._gran(window)
        now = self.walltime()
        aged = 0.0
        for bucket, value in buckets:
            aged += value
            if aged >= excess:
                return max(0.0, (bucket + 1) * gran + window - now)
        return window

    def snapshot(self) -> dict:
        return {
            "buckets": self.BUCKETS,
            "read_ttl_s": self.READ_TTL,
            "publish_errors": self.publish_errors,
            "tracked": len(self._anchors),
        }


class QuotaEnforcer:
    """Admission-side quota enforcement over the usage ledger.

    Event-loop discipline like the scheduler and ledger: all state lives
    on the control plane's single loop; the only IO is the (throttled)
    policy-file stat/read and the one-time journal window restore at
    construction. `admit()` either returns a QuotaVerdict (the caller MUST
    `release()` it on request exit — the concurrency cap's other half) or
    raises QuotaExceededError with the typed reason."""

    def __init__(
        self,
        config=None,
        *,
        usage=None,
        metrics=None,
        walltime=time.time,
        store=None,
    ) -> None:
        from ..config import Config

        self.config = config or Config()
        self.usage = usage
        self.metrics = metrics
        self.walltime = walltime
        self.enabled = bool(self.config.quotas_enabled) and (
            usage is not None and usage.enabled
        )
        # Fleet-coherent windows: engaged only when a SHARED store is
        # wired AND the knob is on — a private store (single replica)
        # keeps admission purely local, zero store ops on the admit path.
        self._fleet = (
            _FleetWindows(store, walltime=walltime)
            if (
                self.enabled
                and store is not None
                and getattr(store, "shared", False)
                and bool(getattr(self.config, "quota_fleet_windows", True))
            )
            else None
        )
        if bool(self.config.quotas_enabled) and not self.enabled:
            # Quotas read exactly the ledger's counters; without metering
            # there is nothing to enforce against. Loud, not silent: an
            # operator who set budgets expects them to bite.
            logger.warning(
                "quota enforcement is inert: it reads the usage ledger and "
                "APP_USAGE_METERING_ENABLED is 0 (or no ledger is wired)"
            )
        self.default_policy = QuotaPolicy(
            chip_seconds_per_window=max(
                0.0, float(self.config.quota_chip_seconds_per_window)
            ),
            hbm_byte_seconds_per_window=max(
                0.0, float(self.config.quota_hbm_byte_seconds)
            ),
            burst_credits=max(0.0, float(self.config.quota_burst_credits)),
            refill_per_second=max(
                0.0, float(self.config.quota_refill_per_second)
            ),
            window_seconds=max(1.0, float(self.config.quota_window_seconds)),
            requests_per_window=max(
                0, int(self.config.quota_requests_per_window)
            ),
            max_concurrent=max(0, int(self.config.quota_max_concurrent)),
            violations_per_window=max(
                0, int(self.config.quota_violations_per_window)
            ),
            quarantine_base_seconds=max(
                1.0, float(self.config.quota_quarantine_base_seconds)
            ),
            quarantine_max_seconds=max(
                1.0, float(self.config.quota_quarantine_max_seconds)
            ),
            quarantine_decay_seconds=max(
                1.0, float(self.config.quota_quarantine_decay_seconds)
            ),
        )
        # The IMMUTABLE config-derived baseline every policy-file load
        # layers over. Layering over the previous load's result instead
        # would make reloads non-idempotent: a key REMOVED from the file
        # would keep its old value on long-running instances while
        # restarted ones revert to config — one file, two fleet policies.
        self._config_default_policy = self.default_policy
        self._tenant_policies: dict[str, QuotaPolicy] = {}
        self._windows: dict[str, _TenantWindow] = {}
        # Policy-file hot reload state.
        self._policy_path = self.config.quota_policy_file or ""
        self._policy_mtime: float | None = None
        self._policy_checked_at = 0.0
        self.policy_loads = 0
        self.policy_load_errors = 0
        self.denials_total = 0
        if not self.enabled:
            return
        self._load_policy_file(force=True)
        if self.usage is not None:
            self._restore_windows()
            self._load_offenders()
        # Restore precision is bounded by the ledger's journal-tail
        # retention: a keep horizon shorter than the largest configured
        # window means post-crash windows can under-count (tenant-
        # favorably) — loud at boot, where the operator can still fix it.
        keep = getattr(self.usage, "journal_keep_seconds", 0.0)
        if 0 < keep < self._max_window():
            logger.warning(
                "usage_journal_keep_seconds (%gs) is shorter than the "
                "largest quota window (%gs): quota windows restored after "
                "a crash may under-count consumption older than the "
                "retained journal tail",
                keep,
                self._max_window(),
            )

    # ---------------------------------------------------------------- policy

    def _load_policy_file(self, *, force: bool = False) -> None:
        """(Re)read APP_QUOTA_POLICY_FILE when its mtime moved, at most
        every quota_policy_reload_seconds. A malformed or vanished file
        keeps the LAST GOOD policy (fail closed, log loudly) — the quota
        layer must not fail open mid-incident because a hot edit tore."""
        if not self._policy_path:
            return
        now = self.walltime()
        if (
            not force
            and now - self._policy_checked_at
            < max(0.1, self.config.quota_policy_reload_seconds)
        ):
            return
        self._policy_checked_at = now
        try:
            mtime = os.stat(self._policy_path).st_mtime
        except OSError:
            if self._policy_mtime is not None or force:
                logger.warning(
                    "quota policy file %s unreadable; keeping the last "
                    "good policy",
                    self._policy_path,
                )
            return
        if mtime == self._policy_mtime and not force:
            return
        try:
            with open(self._policy_path, encoding="utf-8") as f:
                body = json.load(f)
            if not isinstance(body, dict):
                raise ValueError("policy file must be a JSON object")
            # Every load layers over the CONFIG baseline, never over a
            # previous load — reloads are idempotent in file content, so
            # deleting a key from the file really reverts it.
            default = self._config_default_policy
            if "default" in body:
                default = _policy_from_mapping(
                    self._config_default_policy,
                    body["default"],
                    source="default",
                )
            tenants_raw = body.get("tenants", {})
            if not isinstance(tenants_raw, dict):
                raise ValueError("policy file 'tenants' must be an object")
            tenant_policies = {
                str(tenant): _policy_from_mapping(
                    default, overrides, source=f"tenants[{tenant}]"
                )
                for tenant, overrides in tenants_raw.items()
            }
        except (ValueError, OSError) as e:
            self.policy_load_errors += 1
            logger.warning(
                "quota policy file %s rejected (%s); keeping the last "
                "good policy",
                self._policy_path,
                e,
            )
            return
        self.default_policy = default
        self._tenant_policies = tenant_policies
        self._policy_mtime = mtime
        self.policy_loads += 1
        logger.info(
            "quota policy loaded from %s (%d tenant override(s))",
            self._policy_path,
            len(tenant_policies),
        )

    def policy_for(self, tenant: str) -> QuotaPolicy:
        return self._tenant_policies.get(tenant, self.default_policy)

    def _effective_policy(self, tenant: str, label: str) -> QuotaPolicy:
        """THE overflow-policy rule, in one place: a past-the-cap tenant
        shares the overflow ROW, so it shares the overflow row's policy
        view too — unless the operator whitelisted it BY NAME (an explicit
        per-tenant override wins even past the cap). Used by admission,
        the pacing read, and the surfaces, so they can never disagree."""
        if label != tenant and tenant not in self._tenant_policies:
            return self.policy_for(label)
        return self.policy_for(tenant)

    # --------------------------------------------------------------- restore

    def _restore_windows(self) -> None:
        """Rebuild each tenant's sample ring from the ledger's persisted
        history — the quota layer's half of the durability story: budgets
        survive a SIGKILL to within one flush interval, so an offender
        cannot reset its window by crashing the control plane.

        Baseline semantics per tenant: the ring's own prune keeps the
        newest sample at-or-before the window start, so replaying EVERY
        persisted sample in write order yields the exact pre-window
        baseline. When the tenant's first persisted record is a journal
        line with no snapshot row (a new tenant, never compacted), its
        pre-line consumption is exactly ZERO — a synthetic zero baseline
        makes even a single-line burst count in full. A snapshot row's
        pre-history is genuinely gone (folded by compaction), so no
        synthetic baseline is planted there: the error is bounded and
        tenant-favorable (never over-denies)."""
        now = self.walltime()
        per_tenant: dict[str, list[tuple[float, dict]]] = {}
        has_snapshot: set[str] = set()
        for ts, tenant, counters, source in self.usage.iter_persisted():
            if not isinstance(counters.get("chip_seconds"), (int, float)):
                continue
            if source == "snapshot":
                has_snapshot.add(tenant)
            per_tenant.setdefault(tenant, []).append((min(ts, now), counters))
        restored = 0
        for tenant, samples in per_tenant.items():
            # Write order is NOT time order: compaction retains a journal
            # tail OLDER than the snapshot's own ts — the ring needs
            # monotonic timestamps.
            samples.sort(key=lambda s: s[0])
            window = self.policy_for(tenant).window_seconds
            win = self._window(tenant)
            if tenant not in has_snapshot:
                win.observe(samples[0][0] - 1e-3, 0.0, 0.0, window)
            for ts, counters in samples:
                violations = counters.get("violations")
                violations_total = (
                    sum(
                        float(v)
                        for v in violations.values()
                        if isinstance(v, (int, float))
                    )
                    if isinstance(violations, dict)
                    else 0.0
                )
                hbm = counters.get("hbm_byte_seconds")
                win.observe(
                    ts, float(counters["chip_seconds"]), violations_total,
                    window,
                    hbm_cum=float(hbm) if isinstance(hbm, (int, float)) else 0.0,
                )
                restored += 1
        if restored:
            logger.info(
                "quota windows restored from the usage journal "
                "(%d sample(s), %d tenant(s))",
                restored,
                len(self._windows),
            )

    @property
    def _offender_state_path(self) -> str | None:
        """The quarantine ladder's tiny durable sidecar, beside the usage
        journal (same lifecycle, same kill switch). The sample rings
        restore from the journal itself; the ladder (offender level,
        standing sentence, spent-violation floor) is enforcer-local state
        the ledger never holds — without this file, a crash would
        TRUNCATE a standing sentence to a fresh base one, making "crash
        the control plane" a quarantine exploit."""
        journal = self.usage.journal_path if self.usage is not None else None
        if journal is None:
            return None
        # Per-replica shard like the journal itself (one writer per file):
        # two replicas' enforcers rewriting one sidecar would last-writer-
        # wins each other's offender ladders.
        replica = getattr(self.usage, "replica_id", "") or ""
        name = f"quota_state-{replica}.json" if replica else "quota_state.json"
        return os.path.join(os.path.dirname(journal), name)

    def _save_offenders(self) -> None:
        """Persist the non-trivial ladder rows (atomic tmp+rename). Called
        on quarantine transitions and decay writes — rare events by
        construction, so this is never on a healthy request's path. A
        write failure degrades durability, not serving."""
        path = self._offender_state_path
        if path is None:
            return
        rows = {
            label: {
                "offender_level": win.offender_level,
                "quarantined_until": round(win.quarantined_until, 3),
                "violation_floor": round(win.violation_floor, 6),
            }
            for label, win in self._windows.items()
            if win.offender_level > 0 or win.violation_floor > 0
        }
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": 1, "tenants": rows}, f, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            logger.warning("quota offender state not persisted", exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _load_offenders(self) -> None:
        path = self._offender_state_path
        if path is None:
            return
        paths = [path]
        # Turning replication ON must not truncate standing sentences:
        # the ledger's designated legacy inheritor also restores the
        # pre-replication quota_state.json (max-merged under its own
        # shard — the sterner record wins), exactly like the journal.
        if (
            getattr(self.usage, "replica_id", "")
            and getattr(self.usage, "_inherit_legacy", False)
        ):
            legacy = os.path.join(
                os.path.dirname(path), "quota_state.json"
            )
            if legacy != path:
                paths.insert(0, legacy)
        restored = 0
        for source in paths:
            try:
                with open(source, encoding="utf-8") as f:
                    body = json.load(f)
            except FileNotFoundError:
                continue
            except (json.JSONDecodeError, OSError):
                logger.warning(
                    "quota offender state unreadable", exc_info=True
                )
                continue
            tenants = body.get("tenants", {})
            if not isinstance(tenants, dict):
                continue
            for label, row in tenants.items():
                if not isinstance(row, dict):
                    continue
                win = self._window(str(label))
                level = row.get("offender_level")
                until = row.get("quarantined_until")
                floor = row.get("violation_floor")
                if isinstance(level, int) and level >= 0:
                    win.offender_level = max(win.offender_level, level)
                if isinstance(until, (int, float)):
                    win.quarantined_until = max(
                        win.quarantined_until, float(until)
                    )
                if isinstance(floor, (int, float)):
                    win.violation_floor = max(
                        win.violation_floor, float(floor)
                    )
                restored += 1
        if restored:
            logger.info(
                "quota offender ladder restored (%d tenant(s))", restored
            )

    def _max_window(self) -> float:
        windows = [self.default_policy.window_seconds]
        windows += [p.window_seconds for p in self._tenant_policies.values()]
        return max(windows)

    # -------------------------------------------------------------- admission

    def _window(self, label: str) -> _TenantWindow:
        win = self._windows.get(label)
        if win is None:
            win = _TenantWindow()
            self._windows[label] = win
        return win

    def _observe(
        self, label: str, win: _TenantWindow, now: float, window: float
    ) -> None:
        """Sample the ledger row's cumulative counters into the ring."""
        _, row = self.usage.peek(label)
        chip = row.chip_seconds if row is not None else 0.0
        hbm = row.hbm_byte_seconds if row is not None else 0.0
        violations = (
            sum(row.violations.values()) if row is not None else 0.0
        )
        win.observe(now, chip, violations, window, hbm_cum=hbm)
        if self._fleet is not None:
            # Publish this replica's accrual deltas into the fleet
            # buckets — pure increments, so N concurrent publishers
            # compose and the degraded journal can replay them.
            self._fleet.publish_cum(label, "chip", chip, window)
            if hbm > 0:
                self._fleet.publish_cum(label, "hbm", hbm, window)

    def _deny(
        self,
        label: str,
        policy: QuotaPolicy,
        win: _TenantWindow,
        *,
        reason: str,
        retry_after: float,
        detail: str,
        remaining: float | None = None,
        **error_fields,
    ) -> QuotaExceededError:
        win.denials += 1
        self.denials_total += 1
        if self.metrics is not None:
            denials = getattr(self.metrics, "quota_denials", None)
            if denials is not None:
                denials.inc(tenant=label, reason=reason)
        tracing.add_event(
            "quota.denied",
            tenant=label,
            reason=reason,
            retry_after_s=round(max(0.0, retry_after), 3),
        )
        # Rate-limited logging: a denied tenant hammering the door is the
        # EXPECTED load pattern this layer absorbs — one warning per
        # tenant per 10s names the incident; the counter and trace events
        # carry the full rate.
        now = self.walltime()
        if now - win.last_denial_log >= 10.0:
            win.last_denial_log = now
            logger.warning(
                "quota denial (tenant=%s reason=%s retry_after=%.1fs, "
                "%d total): %s",
                label,
                reason,
                retry_after,
                win.denials,
                detail,
            )
        budget = (
            policy.chip_seconds_per_window
            if policy.chip_seconds_per_window > 0
            else None
        )
        return QuotaExceededError(
            f"tenant {label!r} {detail}; retry in {max(0.0, retry_after):.0f}s",
            tenant=label,
            reason=reason,
            retry_after=max(0.0, retry_after),
            remaining_chip_seconds=remaining,
            limit_chip_seconds=budget,
            window_seconds=policy.window_seconds,
            **error_fields,
        )

    def admit(
        self,
        tenant: str | None,
        *,
        predicted_chip_seconds: float | None = None,
    ) -> QuotaVerdict | None:
        """The admission gate, called BEFORE any scheduler/batcher/session
        machinery sees the request. Returns a verdict the caller must
        `release()` on exit, or None when the layer is off / the request
        is unmetered (trusted control-plane runs). Raises
        QuotaExceededError with the typed reason on denial — the request
        is never enqueued.

        `predicted_chip_seconds` is the request's DECLARED worst case
        (chip_count x clamped timeout): with cost prediction on, a request
        whose declaration cannot fit the remaining window budget is denied
        NOW (reason=predicted_overrun, Retry-After from the refill point)
        instead of admitted and billed into overrun — the PR 11 carried
        follow-up."""
        if not self.enabled or tenant is None:
            return None
        self._load_policy_file()
        now = self.walltime()
        label, _ = self.usage.peek(tenant)
        policy = self._effective_policy(tenant, label)
        win = self._window(label)
        if not policy.enforces_anything():
            win.in_flight += 1
            return QuotaVerdict(tenant=label)
        window = policy.window_seconds
        self._observe(label, win, now, window)

        # 1) Quarantine: the standing sentence, checked first — a
        # quarantined tenant's requests never reach any other math.
        if now < win.quarantined_until:
            raise self._deny(
                label,
                policy,
                win,
                reason="quarantined",
                retry_after=win.quarantined_until - now,
                detail=(
                    "is quarantined for repeated limit violations "
                    f"(offender level {win.offender_level})"
                ),
            )
        # Lazy decay: each clean decay-interval since release steps the
        # offender ladder back down (a reformed tenant's next storm earns
        # the base sentence again, not the escalated one).
        if win.offender_level > 0 and win.quarantined_until > 0:
            decayed = int(
                (now - win.quarantined_until)
                / policy.quarantine_decay_seconds
            )
            if decayed > 0:
                win.offender_level = max(0, win.offender_level - decayed)
                win.quarantined_until = (
                    now  # re-anchor so further decay needs further clean time
                    if win.offender_level > 0
                    else 0.0
                )
                # The violation floor deliberately survives full decay:
                # it marks violations a sentence already answered, and
                # those may still sit inside the window — resetting it
                # here would re-quarantine a reformed tenant for old,
                # already-punished violations.
                self._save_offenders()

        # 2) Violation quota: a fresh storm (violations in window past the
        # floor a previous sentence already spent) earns a new sentence.
        if policy.violations_per_window > 0:
            violations = win.violations_in_window(now, window)
            if violations >= policy.violations_per_window:
                win.offender_level += 1
                sentence = min(
                    policy.quarantine_base_seconds
                    * (2.0 ** (win.offender_level - 1)),
                    policy.quarantine_max_seconds,
                )
                win.quarantined_until = now + sentence
                # Spend the window's current violations: the sentence
                # answers THIS storm; only fresh violations after release
                # can earn the next one.
                win.violation_floor = (
                    win.samples[-1][2] if win.samples else 0.0
                )
                win.quarantines += 1
                # Durable: a standing sentence (and the escalation ladder)
                # must survive a control-plane crash — quarantine is the
                # abuse response, and "crash the service" must not be the
                # escape hatch.
                self._save_offenders()
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="quarantined",
                    retry_after=sentence,
                    detail=(
                        f"quarantined: {violations:.0f} limit violations "
                        f"in the last {window:.0f}s (threshold "
                        f"{policy.violations_per_window}, sentence "
                        f"{sentence:.0f}s, episode {win.offender_level})"
                    ),
                )

        # 3) Chip-second budget over the sliding window.
        remaining: float | None = None
        if policy.chip_seconds_per_window > 0:
            used = win.used_chip_seconds(now, window)
            if self._fleet is not None:
                # max, not sum: this replica's consumption is inside both
                # views, so the fleet bound tightens, never double-counts.
                used = max(
                    used, self._fleet.used(label, "chip", window)
                )
            remaining = max(0.0, policy.chip_seconds_per_window - used)
            if used >= policy.chip_seconds_per_window:
                refill_at = win.budget_refill_at(
                    now, window, policy.chip_seconds_per_window
                )
                if self._fleet is not None:
                    refill_at = max(
                        refill_at,
                        now
                        + self._fleet.refill_in(
                            label,
                            "chip",
                            window,
                            policy.chip_seconds_per_window,
                        ),
                    )
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="chip_seconds",
                    retry_after=max(1.0, refill_at - now),
                    detail=(
                        f"exhausted its chip-second budget "
                        f"({used:.3f}s used of "
                        f"{policy.chip_seconds_per_window:.3f}s per "
                        f"{window:.0f}s window)"
                    ),
                    remaining=0.0,
                )
            # 3b) Admission-time cost prediction: the declared worst case
            # (chip_count x timeout) must FIT the remaining budget, or the
            # run would be admitted only to bill into overrun — burning
            # chips the window then shuts everyone out of. Retry-After is
            # the refill point at which the prediction fits; a request
            # bigger than the WHOLE budget can never fit (the client must
            # shrink its declaration) and backs off a full window.
            if (
                self.config.quota_cost_prediction
                and predicted_chip_seconds is not None
                and predicted_chip_seconds > 0
                and predicted_chip_seconds > remaining
            ):
                budget = policy.chip_seconds_per_window
                if predicted_chip_seconds >= budget:
                    refill_at = now + window
                else:
                    refill_at = win.budget_refill_at(
                        now, window, budget - predicted_chip_seconds
                    )
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="predicted_overrun",
                    retry_after=max(1.0, refill_at - now),
                    detail=(
                        f"declared cost ({predicted_chip_seconds:.3f} "
                        f"chip-seconds: chip_count x timeout) cannot fit "
                        f"its remaining budget ({remaining:.3f}s of "
                        f"{budget:.3f}s per {window:.0f}s window)"
                    ),
                    remaining=remaining,
                )

        # 3c) Burst-credit smoothing (opt-in token bucket beside the hard
        # window): the bucket refills continuously at refill_per_second up
        # to burst_credits, and drains by the chip-seconds the ledger has
        # observed since the last admit. An overdrawn bucket denies with a
        # deficit-derived Retry-After — a bursty tenant smooths to the
        # refill rate instead of burning its whole window budget at once
        # and slamming into the window edge for the rest of the hour.
        burst_remaining: float | None = None
        if policy.burst_mode():
            chip_now = win.samples[-1][win.CHIP] if win.samples else 0.0
            if win.burst_level is None:
                # First touch: a full bucket anchored at the tenant's
                # current cumulative consumption (history predating the
                # bucket is the window budget's business, not the bucket's).
                win.burst_level = policy.burst_credits
                win.burst_refill_ts = now
                win.burst_anchor = chip_now
            win.burst_level = min(
                policy.burst_credits,
                win.burst_level
                + max(0.0, now - win.burst_refill_ts)
                * policy.refill_per_second,
            )
            win.burst_refill_ts = now
            drained = max(0.0, chip_now - win.burst_anchor)
            win.burst_anchor = chip_now
            win.burst_level -= drained
            burst_remaining = max(0.0, win.burst_level)
            if win.burst_level <= 0:
                deficit = -win.burst_level
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="burst_credits",
                    retry_after=max(
                        1.0, deficit / policy.refill_per_second
                    ),
                    detail=(
                        f"overdrew its burst credits "
                        f"({deficit:.3f} chip-seconds over; bucket "
                        f"{policy.burst_credits:.3f}, refill "
                        f"{policy.refill_per_second:.3f}/s)"
                    ),
                    remaining=remaining,
                    burst_credits_remaining=0.0,
                )

        # 3d) Device-memory budget over the sliding window: HBM
        # byte-seconds (peak footprint x device-op wall, the PR 14 ledger
        # counter) — the same refill-point Retry-After semantics as
        # chip-seconds, so a memory hog backs off exactly as long as it
        # takes for its own footprint to age out.
        if policy.hbm_byte_seconds_per_window > 0:
            used_hbm = win.used_hbm_byte_seconds(now, window)
            if self._fleet is not None:
                used_hbm = max(
                    used_hbm, self._fleet.used(label, "hbm", window)
                )
            hbm_budget = policy.hbm_byte_seconds_per_window
            if used_hbm >= hbm_budget:
                refill_at = win.budget_refill_at(
                    now, window, hbm_budget, index=win.HBM
                )
                if self._fleet is not None:
                    refill_at = max(
                        refill_at,
                        now
                        + self._fleet.refill_in(
                            label, "hbm", window, hbm_budget
                        ),
                    )
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="hbm_byte_seconds",
                    retry_after=max(1.0, refill_at - now),
                    detail=(
                        f"exhausted its device-memory budget "
                        f"({used_hbm:.0f} HBM byte-seconds used of "
                        f"{hbm_budget:.0f} per {window:.0f}s window)"
                    ),
                    remaining=remaining,
                    remaining_hbm_byte_seconds=0.0,
                    limit_hbm_byte_seconds=hbm_budget,
                )

        # 4) Request rate over the window.
        if policy.requests_per_window > 0:
            win.prune_admits(now, window)
            admitted = len(win.admits)
            if self._fleet is not None:
                admitted = max(
                    admitted, int(self._fleet.used(label, "req", window))
                )
            if admitted >= policy.requests_per_window:
                local_refill = (
                    win.admits[0] + window - now if win.admits else 0.0
                )
                if self._fleet is not None:
                    local_refill = max(
                        local_refill,
                        self._fleet.refill_in(
                            label,
                            "req",
                            window,
                            float(policy.requests_per_window - 1),
                        ),
                    )
                raise self._deny(
                    label,
                    policy,
                    win,
                    reason="request_rate",
                    retry_after=max(1.0, local_refill),
                    detail=(
                        f"exceeded its request-rate cap "
                        f"({policy.requests_per_window} per "
                        f"{window:.0f}s window)"
                    ),
                    remaining=remaining,
                )

        # 5) Concurrency.
        if (
            policy.max_concurrent > 0
            and win.in_flight >= policy.max_concurrent
        ):
            raise self._deny(
                label,
                policy,
                win,
                reason="concurrency",
                retry_after=1.0,
                detail=(
                    f"has {win.in_flight} requests in flight "
                    f"(cap {policy.max_concurrent})"
                ),
                remaining=remaining,
            )

        if policy.requests_per_window > 0:
            win.admits.append(now)
            if self._fleet is not None:
                self._fleet.add(label, "req", 1.0, window)
        win.in_flight += 1
        if policy.chip_seconds_per_window > 0:
            return QuotaVerdict(
                tenant=label,
                remaining_chip_seconds=remaining,
                limit_chip_seconds=policy.chip_seconds_per_window,
                window_seconds=window,
                burst_credits_remaining=burst_remaining,
            )
        return QuotaVerdict(
            tenant=label, burst_credits_remaining=burst_remaining
        )

    def release(self, verdict: QuotaVerdict | None) -> None:
        """Give the concurrency slot back (idempotent — every exit path of
        the executor calls this exactly like usage.commit)."""
        if verdict is None or verdict.released:
            return
        verdict.released = True
        win = self._windows.get(verdict.tenant)
        if win is not None and win.in_flight > 0:
            win.in_flight -= 1

    def refresh_verdict(self, verdict: QuotaVerdict | None) -> None:
        """Post-run pacing refresh (the success-path satellite): recompute
        the verdict's remaining budget against its own ADMIT-TIME
        limit/window, now that this run's bill is in the ledger. The
        verdict's budget, not the label's current policy: a tenant
        whitelisted by name past the cardinality cap is admitted under its
        named override while its consumption accrues to the shared
        `_overflow` row — re-resolving by label would pace it against the
        overflow policy and report a full budget as exhausted."""
        if (
            not self.enabled
            or verdict is None
            or verdict.limit_chip_seconds is None
            or verdict.window_seconds is None
        ):
            return
        now = self.walltime()
        win = self._window(verdict.tenant)
        self._observe(verdict.tenant, win, now, verdict.window_seconds)
        used = win.used_chip_seconds(now, verdict.window_seconds)
        verdict.remaining_chip_seconds = max(
            0.0, verdict.limit_chip_seconds - used
        )

    # --------------------------------------------------------------- surfaces

    def _policy_dict(self, policy: QuotaPolicy) -> dict:
        return {
            "chip_seconds_per_window": policy.chip_seconds_per_window,
            "hbm_byte_seconds_per_window": policy.hbm_byte_seconds_per_window,
            "burst_credits": policy.burst_credits,
            "refill_per_second": policy.refill_per_second,
            "window_seconds": policy.window_seconds,
            "requests_per_window": policy.requests_per_window,
            "max_concurrent": policy.max_concurrent,
            "violations_per_window": policy.violations_per_window,
            "quarantine_base_seconds": policy.quarantine_base_seconds,
            "quarantine_max_seconds": policy.quarantine_max_seconds,
            "quarantine_decay_seconds": policy.quarantine_decay_seconds,
        }

    def tenant_snapshot(self, tenant: str) -> dict | None:
        """One tenant's quota view (GET /quotas/{tenant}); None when the
        layer has never seen it."""
        if not self.enabled:
            return None
        label, _ = self.usage.peek(tenant)
        win = self._windows.get(label)
        if win is None:
            return None
        return self._tenant_body(tenant, label, win)

    def _tenant_body(
        self, tenant: str, label: str, win: _TenantWindow
    ) -> dict:
        policy = self._effective_policy(tenant, label)
        now = self.walltime()
        window = policy.window_seconds
        used = win.used_chip_seconds(now, window)
        win.prune_admits(now, window)
        body: dict = {
            "policy": self._policy_dict(policy),
            "used_chip_seconds_window": round(used, 6),
            "violations_in_window": round(
                win.violations_in_window(now, window), 6
            ),
            "requests_in_window": len(win.admits),
            "in_flight": win.in_flight,
            "offender_level": win.offender_level,
            "quarantined_for_s": round(
                max(0.0, win.quarantined_until - now), 3
            ),
            "denials": win.denials,
            "quarantines": win.quarantines,
        }
        if policy.chip_seconds_per_window > 0:
            body["remaining_chip_seconds"] = round(
                max(0.0, policy.chip_seconds_per_window - used), 6
            )
        if policy.hbm_byte_seconds_per_window > 0:
            used_hbm = win.used_hbm_byte_seconds(now, window)
            body["used_hbm_byte_seconds_window"] = round(used_hbm, 3)
            body["remaining_hbm_byte_seconds"] = round(
                max(0.0, policy.hbm_byte_seconds_per_window - used_hbm), 3
            )
        if policy.burst_mode() and win.burst_level is not None:
            body["burst_credits_remaining"] = round(
                max(0.0, win.burst_level), 6
            )
        return body

    def snapshot(self) -> dict:
        """The GET /quotas body (and the /statusz quotas section)."""
        if not self.enabled:
            return {"enabled": False}
        return {
            "enabled": True,
            "default_policy": self._policy_dict(self.default_policy),
            "tenant_overrides": sorted(self._tenant_policies),
            "policy_file": self._policy_path or None,
            "policy_loads": self.policy_loads,
            "policy_load_errors": self.policy_load_errors,
            "denials_total": self.denials_total,
            "fleet_windows": (
                self._fleet.snapshot() if self._fleet is not None else None
            ),
            "tenants": {
                label: self._tenant_body(label, label, win)
                for label, win in sorted(self._windows.items())
            },
        }

    def remaining_gauge_samples(self) -> dict[tuple[str, ...], float]:
        """Scrape-time feed for the per-tenant remaining-budget gauge.
        Only tenants WITH a chip-second budget emit a sample; labels are
        the ledger's capped row names, so cardinality is bounded by the
        same `_overflow` discipline as every tenant-labeled family."""
        if not self.enabled:
            return {}
        now = self.walltime()
        out: dict[tuple[str, ...], float] = {}
        for label, win in self._windows.items():
            policy = self.policy_for(label)
            if policy.chip_seconds_per_window <= 0:
                continue
            used = win.used_chip_seconds(now, policy.window_seconds)
            out[(label,)] = max(
                0.0, policy.chip_seconds_per_window - used
            )
        return out
