"""Deterministic fault injection for any `SandboxBackend`.

Chaos testing the pool requires failures that are (a) realistic — spawn
errors, slow readiness, refused recycles, hanging deletes, mid-execute
connection drops — and (b) **reproducible**, or a CI chaos run that fails
once can never be debugged. `FaultInjectingBackend` wraps a real backend
with a seeded fault plan: every fault category draws from its own
`random.Random` stream (seeded from the plan seed + category name), so the
spawn-failure sequence does not depend on how exec-drop rolls interleave
with it under concurrency.

The plan is configured as a compact spec string so one env var turns chaos
on in any deployment (``APP_EXECUTOR_FAULT_SPEC=spawn_fail:0.3,seed:7``):

    spawn_fail:<rate>    probability a spawn raises SandboxSpawnError
    slow_ready:<seconds> added latency before a successful spawn returns
    reset_fail:<rate>    probability a reset refuses (returns None)
    delete_hang:<seconds> added latency inside delete()
    exec_drop:<rate>     probability a sandbox HTTP request raises
                         ConnectError mid-flight (via the injectable httpx
                         transport the orchestrator asks backends for)
    violation:<rate>     probability a POST /execute answers with a
                         synthesized typed limit violation instead of
                         running (exercises the LimitExceededError path:
                         422 mapping, no-retry, breaker strikes, host
                         disposal) — kind set by violation_kind
    violation_kind:<kind> which violation to inject (default oom; one of
                         services.limits.VIOLATION_KINDS)
    attach_hang:<rate>   probability a HOST develops a wedged device attach
                         (drawn once per host, at its first GET
                         /device-stats): from then on its stats report an
                         attach pending whose age grows in real time and a
                         stale runner heartbeat — a HANG, not an error,
                         which is the real wedge semantics (BENCH_r03-r05:
                         attaches block for tens of minutes; they do not
                         fail). Drives the probe daemon's
                         healthy→suspect→wedged escalation deterministically.
    attach_hang_lane:<n> restrict attach_hang to hosts of ONE chip-count
                         lane (-1 = any lane, the default) — the chaos e2e
                         wedges one lane while proving the other keeps
                         serving.
    attach_hang_max:<n>  at most n hosts ever wedge (0 = unlimited): with
                         rate 1.0 this wedges exactly the FIRST n hosts a
                         probe touches, so a recovery test can wedge one
                         host deterministically while its dispose-and-
                         replace successor comes up clean.
    attach_hang_recover:<n> a wedged host's hang CLEARS after n wedged
                         /device-stats draws (0 = never, the default):
                         later probes pass through to the real stats.
                         This is the chaos-testable shape of a host that
                         relapses and then recovers — the re-admission
                         streak (clean probes after a fence) and its
                         suspect-relapse reset become drivable from a
                         seeded spec instead of hand-faked responses.
    slow_exec:<rate>     probability an execute dispatch (/execute,
                         /execute/stream, /execute-batch) is DELAYED by
                         slow_exec_seconds before reaching the sandbox —
                         a latency regression, not an error: the request
                         succeeds, only slower. This is the perf anomaly
                         plane's chaos signal (the drift detector must
                         flip the affected lane's exec series to
                         regressed while clean lanes stay normal).
    slow_exec_seconds:<s> the injected delay (default 0.25).
    slow_exec_lane:<n>   restrict slow_exec to hosts of ONE chip-count
                         lane (-1 = any lane, the default) — the perf e2e
                         regresses one lane while proving the other's
                         baseline holds.
    seed:<int>           the plan seed (default 0)

Rates are in [0, 1]; delays are seconds. Unknown keys fail loudly — a typo'd
chaos knob silently injecting nothing is itself a reliability bug.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
from collections.abc import Callable
from dataclasses import dataclass, fields

import httpx

from ..limits import VIOLATION_KINDS
from .base import Sandbox, SandboxBackend, SandboxSpawnError

logger = logging.getLogger(__name__)

SPAWN_FAIL = "spawn_fail"
SLOW_READY = "slow_ready"
RESET_FAIL = "reset_fail"
DELETE_HANG = "delete_hang"
EXEC_DROP = "exec_drop"
VIOLATION = "violation"
ATTACH_HANG = "attach_hang"
SLOW_EXEC = "slow_exec"


@dataclass(frozen=True)
class FaultSpec:
    spawn_fail: float = 0.0
    slow_ready: float = 0.0
    reset_fail: float = 0.0
    delete_hang: float = 0.0
    exec_drop: float = 0.0
    violation: float = 0.0
    violation_kind: str = "oom"
    attach_hang: float = 0.0
    attach_hang_lane: int = -1
    attach_hang_max: int = 0
    attach_hang_recover: int = 0
    slow_exec: float = 0.0
    slow_exec_seconds: float = 0.25
    slow_exec_lane: int = -1
    seed: int = 0

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        """Parse ``key:value,key:value`` (whitespace tolerated). An empty
        string is the null plan (inject nothing)."""
        values: dict[str, float | int | str] = {}
        known = {f.name for f in fields(cls)}
        for item in text.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, raw = item.partition(":")
            key = key.strip()
            if not sep or key not in known:
                raise ValueError(
                    f"bad fault spec item {item!r}: want one of "
                    f"{sorted(known)} as key:value"
                )
            try:
                if key in (
                    "seed",
                    "attach_hang_lane",
                    "attach_hang_max",
                    "attach_hang_recover",
                    "slow_exec_lane",
                ):
                    values[key] = int(raw)
                elif key == "violation_kind":
                    values[key] = raw.strip()
                else:
                    values[key] = float(raw)
            except ValueError:
                raise ValueError(
                    f"bad fault spec value for {key}: {raw!r}"
                ) from None
        spec = cls(**values)
        for name in (
            SPAWN_FAIL,
            RESET_FAIL,
            EXEC_DROP,
            VIOLATION,
            ATTACH_HANG,
            SLOW_EXEC,
        ):
            rate = getattr(spec, name)
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"fault rate {name} must be in [0,1]: {rate}")
        for name in (SLOW_READY, DELETE_HANG, "slow_exec_seconds"):
            if getattr(spec, name) < 0.0:
                raise ValueError(f"fault delay {name} must be >= 0")
        if spec.violation_kind not in VIOLATION_KINDS:
            raise ValueError(
                f"violation_kind must be one of {list(VIOLATION_KINDS)}: "
                f"{spec.violation_kind!r}"
            )
        return spec

    @property
    def active(self) -> bool:
        return any(
            getattr(self, f.name)
            for f in fields(self)
            if f.name
            not in (
                "seed",
                "violation_kind",
                "attach_hang_lane",
                "attach_hang_max",
                "attach_hang_recover",
                "slow_exec_seconds",
                "slow_exec_lane",
            )
        )


class ViolationTransport(httpx.AsyncBaseTransport):
    """httpx transport that answers a seeded fraction of POST /execute
    calls with a synthesized typed-limit-violation response — the body a
    real executor returns after its watchdog killed the runner group —
    without the request ever reaching a sandbox. This drives the whole
    control-plane classification path (LimitExceededError, 422 mapping,
    no-retry, breaker strike, host disposal) deterministically in chaos
    runs."""

    def __init__(
        self,
        rate: float,
        kind: str,
        rng: random.Random,
        on_fault: Callable[[str], None] | None = None,
        inner: httpx.AsyncBaseTransport | None = None,
    ) -> None:
        self.rate = rate
        self.kind = kind
        self.rng = rng
        self.on_fault = on_fault
        self.inner = inner or httpx.AsyncHTTPTransport()

    async def handle_async_request(self, request):
        if (
            request.method == "POST"
            and request.url.path == "/execute"
            and self.rng.random() < self.rate
        ):
            if self.on_fault is not None:
                self.on_fault(VIOLATION)
            # cpu_time is the one kind the in-process guard catches with the
            # runner surviving; every other kind is a watchdog group kill.
            killed = self.kind != "cpu_time"
            body = {
                "stdout": "",
                "stderr": f"Resource limit exceeded: {self.kind} (injected)",
                "exit_code": 137 if killed else 1,
                "stdout_truncated": False,
                "stderr_truncated": False,
                "violation": self.kind,
                "files": [],
                "deleted": [],
                "duration_s": 0.0,
                "warm": True,
                "runner_restarted": killed,
            }
            return httpx.Response(200, json=body, request=request)
        return await self.inner.handle_async_request(request)

    async def aclose(self) -> None:
        await self.inner.aclose()


class AttachHangTransport(httpx.AsyncBaseTransport):
    """httpx transport that gives a seeded subset of hosts a wedged device
    attach, as seen through ``GET /device-stats``: once a host is chosen
    (one draw at its first stats probe; optionally restricted to one lane),
    every later probe of that host gets a synthesized body whose
    ``attach_pending_s`` grows in REAL time from the moment the hang
    started, with a matching stale runner heartbeat. A hang, not an error —
    the executor's HTTP plane stays perfectly responsive while the device
    plane silently stops, which is exactly the BENCH_r03-r05 wedge the
    probe daemon must distinguish from ordinary busy/attaching states.
    Everything except /device-stats passes through untouched (detection is
    this PR's scope; the data plane keeps serving)."""

    def __init__(
        self,
        rate: float,
        lane: int,
        rng: random.Random,
        host_lanes: dict[str, int],
        on_fault: Callable[[str], None] | None = None,
        inner: httpx.AsyncBaseTransport | None = None,
        clock: Callable[[], float] = time.monotonic,
        max_hosts: int = 0,
        recover_draws: int = 0,
    ) -> None:
        self.rate = rate
        self.lane = lane
        self.rng = rng
        # "host:port" -> chip-count lane, recorded by the backend at spawn:
        # the lane restriction must hold even though a URL alone says
        # nothing about topology.
        self.host_lanes = host_lanes
        self.on_fault = on_fault
        self.inner = inner or httpx.AsyncHTTPTransport()
        self.clock = clock
        # At most this many hosts ever wedge (0 = unlimited): with rate 1.0
        # the FIRST max_hosts probed hosts wedge deterministically and the
        # dispose-and-replace successors come up clean — the recovery e2e's
        # wedge-one-host shape.
        self.max_hosts = max_hosts
        # A wedged host's hang clears after this many wedged stats draws
        # (0 = never): the chaos-testable relapse-then-recover host the
        # re-admission streak needs.
        self.recover_draws = recover_draws
        # "host:port" -> hang start (clock), or None for hosts that drew a
        # pass. One draw per host, remembered — a wedge does not flicker
        # (with recover_draws set it can only CLEAR, once, for good).
        self._hangs: dict[str, float | None] = {}
        self._wedged_draws: dict[str, int] = {}

    def _hang_started(self, request) -> float | None:
        key = f"{request.url.host}:{request.url.port}"
        if key not in self._hangs:
            lane = self.host_lanes.get(key)
            eligible = self.lane < 0 or (lane is not None and lane == self.lane)
            if eligible and self.max_hosts > 0:
                wedged_hosts = sum(
                    1 for start in self._hangs.values() if start is not None
                )
                eligible = wedged_hosts < self.max_hosts
            wedged = eligible and self.rng.random() < self.rate
            self._hangs[key] = self.clock() if wedged else None
            if wedged and self.on_fault is not None:
                self.on_fault(ATTACH_HANG)
        started = self._hangs[key]
        if started is not None and self.recover_draws > 0:
            draws = self._wedged_draws.get(key, 0)
            if draws >= self.recover_draws:
                return None  # the hang cleared: real stats from here on
            self._wedged_draws[key] = draws + 1
        return started

    async def handle_async_request(self, request):
        if (
            request.method == "GET"
            and request.url.path == "/device-stats"
        ):
            started = self._hang_started(request)
            if started is not None:
                age = max(0.0, self.clock() - started)
                body = {
                    "status": "ok",
                    "warm": False,
                    "warm_state": "pending",
                    "backend": "none",
                    "device_kind": "",
                    "device_count": 0,
                    "num_hosts": 1,
                    "uptime_s": age,
                    # THE wedge signature: an attach that has been pending
                    # for `age` seconds and counting, no runner heartbeat.
                    "attach_pending_s": age,
                    "attach_seconds": -1.0,
                    "op_in_flight": False,
                    "op_age_s": 0.0,
                    "op_timeout_s": 0.0,
                    "last_device_op_age_s": -1.0,
                    "runner_heartbeat_age_s": age,
                    "runner_alive": False,
                    "runner_pid": 0,
                    "rss_bytes": -1,
                    "runner_rss_bytes": -1,
                    "injected": ATTACH_HANG,
                }
                return httpx.Response(200, json=body, request=request)
        return await self.inner.handle_async_request(request)

    async def aclose(self) -> None:
        await self.inner.aclose()


class SlowExecTransport(httpx.AsyncBaseTransport):
    """httpx transport that DELAYS a seeded fraction of execute dispatches
    (/execute, /execute/stream, /execute-batch) before they reach the
    sandbox — a latency regression, not an error: the request succeeds,
    only slower. Optionally restricted to one chip-count lane via the
    backend's host→lane map, so a chaos leg can regress one lane while
    the control plane proves the others' baselines hold. This is the perf
    anomaly plane's chaos signal: the drift detector must flip the
    affected (lane, exec) series to regressed within one window."""

    _EXEC_PATHS = ("/execute", "/execute/stream", "/execute-batch")

    def __init__(
        self,
        rate: float,
        delay_s: float,
        lane: int,
        rng: random.Random,
        host_lanes: dict[str, int],
        on_fault: Callable[[str], None] | None = None,
        inner: httpx.AsyncBaseTransport | None = None,
    ) -> None:
        self.rate = rate
        self.delay_s = delay_s
        self.lane = lane
        self.rng = rng
        self.host_lanes = host_lanes
        self.on_fault = on_fault
        self.inner = inner or httpx.AsyncHTTPTransport()

    async def handle_async_request(self, request):
        if (
            request.method == "POST"
            and request.url.path in self._EXEC_PATHS
        ):
            key = f"{request.url.host}:{request.url.port}"
            lane = self.host_lanes.get(key)
            eligible = self.lane < 0 or (
                lane is not None and lane == self.lane
            )
            # The draw happens for EVERY dispatch (eligible or not) so the
            # seeded stream's consumption — and therefore every other
            # category's interleaving — does not depend on which lane a
            # request happened to land on.
            fired = self.rng.random() < self.rate
            if eligible and fired:
                if self.on_fault is not None:
                    self.on_fault(SLOW_EXEC)
                await asyncio.sleep(self.delay_s)
        return await self.inner.handle_async_request(request)

    async def aclose(self) -> None:
        await self.inner.aclose()


class DroppingTransport(httpx.AsyncBaseTransport):
    """httpx transport that raises `httpx.ConnectError` on a seeded fraction
    of requests before delegating to the real transport — the mid-execute
    connection drop no backend-level fault can produce (the request dies on
    the wire, not in the sandbox)."""

    def __init__(
        self,
        rate: float,
        rng: random.Random,
        on_fault: Callable[[str], None] | None = None,
        inner: httpx.AsyncBaseTransport | None = None,
    ) -> None:
        self.rate = rate
        self.rng = rng
        self.on_fault = on_fault
        self.inner = inner or httpx.AsyncHTTPTransport()

    async def handle_async_request(self, request):
        if self.rng.random() < self.rate:
            if self.on_fault is not None:
                self.on_fault(EXEC_DROP)
            raise httpx.ConnectError(
                f"injected connection drop ({request.url})", request=request
            )
        return await self.inner.handle_async_request(request)

    async def aclose(self) -> None:
        await self.inner.aclose()


STORE_DROP = "store_drop"
STORE_OUTAGE = "store_outage"


@dataclass(frozen=True)
class StoreFaultSpec:
    """Seeded fault plan for the shared StateStore, configured via
    ``APP_STATE_STORE_FAULT_SPEC`` with the same ``key:value,...`` grammar
    as the backend plan:

        drop:<rate>       probability any single store op raises
                          StateStoreUnavailableError (flaky network)
        outage_after:<n>  after n successful ops, the store goes HARD
                          down (every op fails) — 0 disables
        outage_ops:<n>    the outage clears after n failed ops (0 = it
                          never clears): the deterministic
                          outage-then-reconnect shape the degraded-mode
                          tests replay
        seed:<int>        the plan seed (default 0)

    A PARTITION (one replica loses the store while peers keep it) is
    staged by wrapping only that replica's store handle — the injector
    wraps a handle, not the server.
    """

    drop: float = 0.0
    outage_after: int = 0
    outage_ops: int = 0
    seed: int = 0

    @classmethod
    def parse(cls, text: str) -> "StoreFaultSpec":
        values: dict[str, float | int] = {}
        known = {f.name for f in fields(cls)}
        for item in text.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, raw = item.partition(":")
            key = key.strip()
            if not sep or key not in known:
                raise ValueError(
                    f"bad store fault spec item {item!r}: want one of "
                    f"{sorted(known)} as key:value"
                )
            try:
                values[key] = (
                    float(raw) if key == "drop" else int(raw)
                )
            except ValueError:
                raise ValueError(
                    f"bad store fault spec value for {key}: {raw!r}"
                ) from None
        spec = cls(**values)
        if not 0.0 <= spec.drop <= 1.0:
            raise ValueError(f"store drop rate must be in [0,1]: {spec.drop}")
        if spec.outage_after < 0 or spec.outage_ops < 0:
            raise ValueError("store outage counters must be >= 0")
        return spec

    @property
    def active(self) -> bool:
        return self.drop > 0.0 or self.outage_after > 0


class FaultInjectingStateStore:
    """Wraps any StateStore with the seeded StoreFaultSpec: per-op drop
    rolls from a dedicated stream plus a deterministic hard-outage window
    (``outage_after`` successes, then ``outage_ops`` failures, then
    healthy again). Duck-types the StateStore interface — components only
    call the ops, and ``make_state_store`` layers ResilientStateStore
    OUTSIDE this wrapper so degraded-mode policy sees the injected
    failures exactly as it would see real ones."""

    def __init__(
        self,
        inner,
        spec: StoreFaultSpec,
        *,
        on_fault: Callable[[str], None] | None = None,
    ) -> None:
        self.inner = inner
        self.spec = spec
        self.on_fault = on_fault
        self._rng = random.Random(f"{spec.seed}:store")
        self._lock = threading.Lock()
        self._ops = 0
        self._outage_left = 0
        self._in_outage = False
        if spec.active:
            logger.warning("state-store fault injection ACTIVE: %s", spec)

    @property
    def shared(self) -> bool:
        return self.inner.shared

    def _gate(self) -> None:
        # Imported here (not top-level) to keep the module import-light for
        # backend-only users; state_store imports THIS module lazily for
        # the same reason.
        from ..state_store import StateStoreUnavailableError

        with self._lock:
            if self._in_outage:
                if self.spec.outage_ops > 0:
                    self._outage_left -= 1
                    if self._outage_left <= 0:
                        # The outage clears AFTER this last failed op; the
                        # success counter restarts so a later window can
                        # re-trip deterministically.
                        self._in_outage = False
                        self._ops = 0
                if self.on_fault is not None:
                    self.on_fault(STORE_OUTAGE)
                raise StateStoreUnavailableError(
                    f"injected store outage (seed={self.spec.seed})"
                )
            if self.spec.outage_after > 0:
                self._ops += 1
                if self._ops > self.spec.outage_after:
                    self._in_outage = True
                    self._outage_left = self.spec.outage_ops
                    if self.on_fault is not None:
                        self.on_fault(STORE_OUTAGE)
                    raise StateStoreUnavailableError(
                        f"injected store outage (seed={self.spec.seed})"
                    )
            if self.spec.drop > 0.0 and self._rng.random() < self.spec.drop:
                if self.on_fault is not None:
                    self.on_fault(STORE_DROP)
                raise StateStoreUnavailableError(
                    f"injected store drop (seed={self.spec.seed})"
                )

    def get(self, ns, key):
        self._gate()
        return self.inner.get(ns, key)

    def put(self, ns, key, value):
        self._gate()
        return self.inner.put(ns, key, value)

    def delete(self, ns, key):
        self._gate()
        return self.inner.delete(ns, key)

    def items(self, ns):
        self._gate()
        return self.inner.items(ns)

    def incr(self, ns, key, delta=1.0):
        self._gate()
        return self.inner.incr(ns, key, delta)

    def mutate(self, ns, key, fn):
        self._gate()
        return self.inner.mutate(ns, key, fn)

    # TTL-lease helpers ride the gated primitives via the base-class
    # implementations on the INNER store — but they must go through OUR
    # gate, so delegate explicitly.
    def put_ttl(self, ns, key, value, ttl_seconds, *, now=None):
        self._gate()
        return self.inner.put_ttl(ns, key, value, ttl_seconds, now=now)

    def get_live(self, ns, key, *, now=None):
        self._gate()
        return self.inner.get_live(ns, key, now=now)

    def acquire_lease(self, ns, key, owner, ttl_seconds, *, now=None):
        self._gate()
        return self.inner.acquire_lease(ns, key, owner, ttl_seconds, now=now)

    def close(self):
        self.inner.close()


class FaultInjectingBackend(SandboxBackend):
    """Wraps any backend with the seeded fault plan above. Transparent when
    the plan is null; delete() never raises (base-class contract) even while
    injecting hangs."""

    def __init__(
        self,
        inner: SandboxBackend,
        spec: FaultSpec,
        *,
        on_fault: Callable[[str], None] | None = None,
    ) -> None:
        self.inner = inner
        self.spec = spec
        self.on_fault = on_fault
        self._rngs = {
            name: random.Random(f"{spec.seed}:{name}")
            for name in (
                SPAWN_FAIL,
                SLOW_READY,
                RESET_FAIL,
                DELETE_HANG,
                EXEC_DROP,
                VIOLATION,
                ATTACH_HANG,
                SLOW_EXEC,
            )
        }
        # "host:port" -> lane, recorded at spawn so the attach-hang
        # transport can honor a lane restriction.
        self._host_lanes: dict[str, int] = {}
        if spec.active:
            logger.warning("fault injection ACTIVE: %s", spec)

    def bind_breakers(self, board) -> None:
        """Pass the executor's breaker board through to the wrapped backend
        (the kubernetes pod-watch integration must keep working under an
        injected-fault wrapper)."""
        bind = getattr(self.inner, "bind_breakers", None)
        if bind is not None:
            bind(board)

    @property
    def compile_cache_dir_scope(self) -> str:
        """The wrapper injects faults, it doesn't change who can write the
        cache dir — delegate the trust statement to the real backend
        (fail-closed "external" if it declares nothing)."""
        scope = getattr(self.inner, "compile_cache_dir_scope", None)
        return scope if scope in ("private", "shared") else "external"

    @property
    def supports_lease_push(self) -> bool:
        """Whether this backend's sandboxes are real HTTP hosts the lease
        token can be POSTed to — delegated (the in-memory test fake says
        no, so chaos runs stay deterministic)."""
        return getattr(self.inner, "supports_lease_push", True)

    def lease_scope(self, chip_count: int, sandbox=None):
        """Hardware lease-scope naming — delegated (the wrapper changes
        fault behavior, not which chips a sandbox holds). None (falsy)
        when the inner backend declares nothing: the executor then uses
        its lane default."""
        scope_fn = getattr(self.inner, "lease_scope", None)
        if scope_fn is None:
            return None
        try:
            return scope_fn(chip_count, sandbox=sandbox)
        except TypeError:
            return scope_fn(chip_count)

    def _fire(self, name: str, rate: float) -> bool:
        if rate <= 0.0 or self._rngs[name].random() >= rate:
            return False
        if self.on_fault is not None:
            self.on_fault(name)
        return True

    # ---------------------------------------------------------------- backend

    async def spawn(self, chip_count: int = 0) -> Sandbox:
        if self._fire(SPAWN_FAIL, self.spec.spawn_fail):
            raise SandboxSpawnError(
                f"injected spawn failure (lane={chip_count}, "
                f"seed={self.spec.seed})"
            )
        if self.spec.slow_ready > 0.0:
            self._fire(SLOW_READY, 1.0)  # counted, never skipped
            await asyncio.sleep(self.spec.slow_ready)
        sandbox = await self.inner.spawn(chip_count)
        if self.spec.attach_hang > 0.0 or self.spec.slow_exec > 0.0:
            # Both lane-restrictable transports key off "host:port": record
            # the lane at spawn, where topology is still known.
            for url in sandbox.host_urls:
                parsed = httpx.URL(url)
                self._host_lanes[f"{parsed.host}:{parsed.port}"] = chip_count
        return sandbox

    def pool_capacity(self, chip_count: int) -> int | None:
        capacity_fn = getattr(self.inner, "pool_capacity", None)
        return capacity_fn(chip_count) if capacity_fn is not None else None

    async def reset(self, sandbox: Sandbox) -> Sandbox | None:
        if self._fire(RESET_FAIL, self.spec.reset_fail):
            return None
        return await self.inner.reset(sandbox)

    async def delete(self, sandbox: Sandbox) -> None:
        if self.spec.delete_hang > 0.0:
            self._fire(DELETE_HANG, 1.0)
            await asyncio.sleep(self.spec.delete_hang)
        await self.inner.delete(sandbox)

    async def close(self) -> None:
        await self.inner.close()

    # ------------------------------------------------------------- http hook

    def http_transport(self) -> httpx.AsyncBaseTransport | None:
        """Transport the orchestrator should build its sandbox HTTP client
        with (None = default). This is how exec_drop and violation reach
        the wire; both active stacks them (violation checked first)."""
        transport: httpx.AsyncBaseTransport | None = None
        if self.spec.exec_drop > 0.0:
            transport = DroppingTransport(
                self.spec.exec_drop, self._rngs[EXEC_DROP], self.on_fault
            )
        if self.spec.violation > 0.0:
            transport = ViolationTransport(
                self.spec.violation,
                self.spec.violation_kind,
                self._rngs[VIOLATION],
                self.on_fault,
                inner=transport,
            )
        if self.spec.attach_hang > 0.0:
            transport = AttachHangTransport(
                self.spec.attach_hang,
                self.spec.attach_hang_lane,
                self._rngs[ATTACH_HANG],
                self._host_lanes,
                self.on_fault,
                inner=transport,
                max_hosts=self.spec.attach_hang_max,
                recover_draws=self.spec.attach_hang_recover,
            )
        if self.spec.slow_exec > 0.0:
            transport = SlowExecTransport(
                self.spec.slow_exec,
                self.spec.slow_exec_seconds,
                self.spec.slow_exec_lane,
                self._rngs[SLOW_EXEC],
                self._host_lanes,
                self.on_fault,
                inner=transport,
            )
        return transport
