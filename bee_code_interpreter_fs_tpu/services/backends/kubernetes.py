"""Kubernetes sandbox backend: single-use executor pods on TPU-slice nodes.

Behavior parity with the reference's pod management
(src/code_interpreter/services/kubernetes_code_executor.py:203-279) —
ownerReferences for cascading GC (:230-239), ``app=code-executor`` label
(:227-229), random 6-char name suffix (:216-218), image/resources/pod-spec
merge hooks (:241-251), Ready wait with bounded timeout (:254-256), delete on
failed spawn (:257-261) — re-designed TPU-first:

- ``chip_count`` drives scheduling: the container gets a ``google.com/tpu``
  resource request/limit and the pod gets the configured TPU accelerator /
  topology nodeSelector, so a 4-chip lane actually lands on a v5e-4 slice.
- The executor container starts its warm JAX runner at boot (executor/
  runner.py), so pool residency time — not the Execute critical path —
  absorbs libtpu init; a shared JAX compilation-cache volume/path persists
  XLA compiles across pod generations (SURVEY.md §7 hard part #2).
- No path-joining accidents: the control plane talks to ``podIP:8000`` with
  workspace-relative paths (the reference's absolute-path collapse bug,
  SURVEY.md §0.4, does not exist here).
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid
from typing import Any

from ...config import Config
from ..kubectl import Kubectl, KubectlError
from .base import Sandbox, SandboxBackend, SandboxSpawnError

logger = logging.getLogger(__name__)

EXECUTOR_PORT = 8000


def deep_merge(base: dict, extra: dict) -> dict:
    """Recursive dict merge (extra wins); lists are concatenated — matches
    how the reference splices ``executor_pod_spec_extra`` into the spec."""
    out = dict(base)
    for key, value in extra.items():
        if key in out and isinstance(out[key], dict) and isinstance(value, dict):
            out[key] = deep_merge(out[key], value)
        elif key in out and isinstance(out[key], list) and isinstance(value, list):
            out[key] = out[key] + value
        else:
            out[key] = value
    return out


class KubernetesSandboxBackend(SandboxBackend):
    def __init__(
        self,
        config: Config | None = None,
        *,
        kubectl: Kubectl | None = None,
        numpy_dispatch: bool = True,
    ) -> None:
        self.config = config or Config()
        self.kubectl = kubectl or Kubectl()
        self.numpy_dispatch = numpy_dispatch
        self._owner_ref: dict | None | bool = None  # None = not looked up yet
        self._owner_lock = asyncio.Lock()
        self._live: dict[str, Sandbox] = {}

    # ------------------------------------------------------------ manifest

    async def _owner_reference(self) -> dict | None:
        """ownerReference to our own pod → orphaned executor pods are
        garbage-collected if the control plane dies (reference :230-239).
        Outside a cluster (no HOSTNAME pod), pods are simply unowned."""
        async with self._owner_lock:
            if self._owner_ref is None:
                hostname = os.environ.get("HOSTNAME", "")
                try:
                    me = await self.kubectl.get("pod", hostname) if hostname else None
                    self._owner_ref = me and {
                        "apiVersion": "v1",
                        "kind": "Pod",
                        "name": me["metadata"]["name"],
                        "uid": me["metadata"]["uid"],
                        "blockOwnerDeletion": False,
                    }
                except KubectlError:
                    logger.warning(
                        "could not resolve own pod %r; executor pods will be "
                        "unowned (no cascading GC)",
                        hostname,
                    )
                    self._owner_ref = False
            return self._owner_ref or None

    def pod_manifest(self, name: str, chip_count: int, owner: dict | None) -> dict:
        resources = deep_merge({}, self.config.executor_container_resources)
        spec: dict[str, Any] = {}
        if chip_count > 0:
            tpu = self.config.tpu_resource_requests or {"google.com/tpu": None}
            chip_resources = {
                key: str(chip_count) if value is None else str(value)
                for key, value in tpu.items()
            }
            resources = deep_merge(
                resources,
                {"limits": dict(chip_resources), "requests": dict(chip_resources)},
            )
            if self.config.tpu_node_selector:
                spec["nodeSelector"] = dict(self.config.tpu_node_selector)

        env = [
            {"name": "APP_LISTEN_ADDR", "value": f"0.0.0.0:{EXECUTOR_PORT}"},
            {
                "name": "APP_WARM_RUNNER",
                "value": "1" if self.config.executor_warm_runner else "0",
            },
            {"name": "APP_CHIP_COUNT", "value": str(chip_count)},
        ]
        if self.config.jax_compilation_cache_dir:
            env.append(
                {
                    "name": "JAX_COMPILATION_CACHE_DIR",
                    "value": self.config.jax_compilation_cache_dir,
                }
            )
        if self.numpy_dispatch:
            env.append({"name": "APP_NUMPY_DISPATCH", "value": "1"})

        spec = deep_merge(
            {
                "containers": [
                    {
                        "name": "executor",
                        "image": self.config.executor_image,
                        "ports": [{"containerPort": EXECUTOR_PORT}],
                        "env": env,
                        "resources": resources,
                        # The executor only starts listening once its warm
                        # JAX runner finished libtpu init, so Ready really
                        # means "hot TPU, ready for user code".
                        "readinessProbe": {
                            "httpGet": {"path": "/healthz", "port": EXECUTOR_PORT},
                            "periodSeconds": 1,
                            "failureThreshold": 120,
                        },
                    }
                ],
                "restartPolicy": "Never",
                **spec,
            },
            self.config.executor_pod_spec_extra,
        )
        metadata: dict[str, Any] = {
            "name": name,
            "labels": {
                "app": "code-executor",
                "code-executor/chip-count": str(chip_count),
            },
        }
        if owner:
            metadata["ownerReferences"] = [owner]
        return {"apiVersion": "v1", "kind": "Pod", "metadata": metadata, "spec": spec}

    # ------------------------------------------------------------ lifecycle

    async def spawn(self, chip_count: int = 0) -> Sandbox:
        name = self.config.executor_pod_name_prefix + uuid.uuid4().hex[:6]
        owner = await self._owner_reference()
        manifest = self.pod_manifest(name, chip_count, owner)
        try:
            await self.kubectl.create(manifest)
        except KubectlError as e:
            raise SandboxSpawnError(f"pod {name} create failed: {e}") from e
        try:
            await self.kubectl.wait(
                "pod",
                name,
                **{"for": "condition=Ready"},
                timeout=f"{int(self.config.executor_pod_ready_timeout)}s",
            )
            pod = await self.kubectl.get("pod", name)
            pod_ip = pod["status"].get("podIP")
            if not pod_ip:
                raise SandboxSpawnError(f"pod {name} Ready but has no podIP")
        except (KubectlError, SandboxSpawnError) as e:
            # Failed spawn must not leak a pod (reference :257-261).
            asyncio.ensure_future(self.delete_by_name(name))
            raise SandboxSpawnError(f"pod {name} did not become ready: {e}") from e
        sandbox = Sandbox(
            id=name,
            url=f"http://{pod_ip}:{EXECUTOR_PORT}",
            chip_count=chip_count,
            meta={"pod_ip": pod_ip},
        )
        self._live[name] = sandbox
        logger.info("spawned executor pod %s (%d chips) at %s", name, chip_count, pod_ip)
        return sandbox

    async def delete_by_name(self, name: str) -> None:
        self._live.pop(name, None)
        try:
            await self.kubectl.delete("pod", name, wait=False)
        except KubectlError as e:
            logger.warning("pod %s delete failed: %s", name, e)

    async def delete(self, sandbox: Sandbox) -> None:
        await self.delete_by_name(sandbox.id)

    async def close(self) -> None:
        await asyncio.gather(
            *(self.delete_by_name(name) for name in list(self._live)),
            return_exceptions=True,
        )
